"""CEP — complex event processing (pattern matching on keyed streams).

ref: flink-libraries/flink-cep (Pattern.begin/next/followedBy/where/
within → NFACompiler → CepOperator keeping per-key NFA state +
partial-match buffers in keyed state).

TPU-first redesign: the reference walks one NFA per key per RECORD.
Here the per-key automaton state is COLUMNS over key slots (current
stage, window-start ts, per-stage match timestamps), and a microbatch
is processed by WITHIN-KEY RANK: sort by (key, ts), then step r
advances EVERY key's automaton on its r-th event of the batch at once —
the sequential dependence lives only along each key's own event chain,
so the loop length is the longest per-key run in the batch while each
step is one vectorized transition over all keys.

Supported semantics (a deterministic, documented subset of the
reference's full NFA):
- linear patterns: ``begin(a).next(b)`` (STRICT contiguity — the very
  next event of that key must match or the partial resets) and
  ``followed_by`` (RELAXED — non-matching events in between are
  skipped), with vectorized ``where`` predicates per stage;
- ``within(ms)``: a partial older than the window resets (the event
  that broke it may immediately start a new partial);
- negation (ref: Pattern.notNext / Pattern.notFollowedBy):
  ``not_next(name)`` — the key's immediately-next event must NOT match
  (a match kills the partial; any other event satisfies the constraint
  and is immediately re-tested against the following stage);
  ``not_followed_by(name)`` — NO matching event may occur strictly
  between the surrounding stages (an event matching both the forbidden
  predicate and the following stage counts as the following stage — no
  forbidden event occurred strictly between). A TRAILING
  not_followed_by turns ``within(ms)`` into an absence window: the
  match completes when the watermark (or a later in-stream event of
  the same key) passes ``match_start + within`` with no forbidden
  event seen; ``match_end`` is that deadline and the negated stage's
  ``<name>_ts`` column reads -1. Negated stages cannot be quantified,
  cannot begin a pattern, and run on the default single-partial engine
  only (the multi-partial after-match modes below refuse them);
- after-match skipping (ref: cep/nfa/aftermatch/AfterMatchSkipStrategy):
  SKIP_PAST_LAST_EVENT (default — each event belongs to at most one
  match, matches never overlap); ``after_match("NO_SKIP")`` —
  overlapping matches enumerated from a BOUNDED per-key partial buffer
  (``max_partials`` columns, loud overflow; linear patterns only —
  quantified patterns with NO_SKIP would need the reference's
  exponential SharedBuffer branch enumeration and are refused at
  build); ``after_match("SKIP_TO_FIRST", "b")`` /
  ``after_match("SKIP_TO_LAST", "b")`` — run on the same multi-partial
  engine, but each completed match prunes every partial (and any
  not-yet-emitted same-event completion) whose start precedes the
  first/last event the match mapped to stage ``b``;
- default mode keeps one active partial per key (greedy earliest): no
  simultaneous alternative partials. A failed strict transition
  re-tests the breaking event against stage 0.

Matches emit one row per completed pattern: key, ``<stage>_ts`` per
stage, and the match's start/end timestamps.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.state.keyed import KeyDirectory, account_full_drop
from flink_tpu.time.watermarks import LONG_MIN


@dataclasses.dataclass(frozen=True)
class _Stage:
    name: str
    where: Optional[Callable[[Dict[str, np.ndarray]], np.ndarray]]
    strict: bool  # True = next() contiguity; False = followed_by()
    times: int = 1        # expand into this many copies (times(n))
    loop: bool = False    # oneOrMore: greedy unbounded repetition
    optional: bool = False  # may be skipped when the NEXT stage matches
    negated: bool = False   # not_next / not_followed_by: a match KILLS


class Pattern:
    """Fluent pattern builder (ref: cep/pattern/Pattern.java)."""

    def __init__(self, stages: Tuple[_Stage, ...],
                 within_ms: Optional[int] = None,
                 after_match_mode: str = "SKIP_PAST_LAST_EVENT",
                 after_match_stage: Optional[str] = None):
        self._stages = stages
        self.within_ms = within_ms
        self.after_match_mode = after_match_mode
        self.after_match_stage = after_match_stage

    def _with(self, stages: Tuple[_Stage, ...]) -> "Pattern":
        return Pattern(stages, self.within_ms, self.after_match_mode,
                       self.after_match_stage)

    @classmethod
    def begin(cls, name: str) -> "Pattern":
        return cls((_Stage(name, None, strict=False),))

    def where(self, pred: Callable[[Dict[str, np.ndarray]], np.ndarray]) -> "Pattern":
        """Vectorized predicate over the batch's field arrays → (B,)
        bool. Applies to the most recent stage (for a negated stage it
        is the FORBIDDEN shape)."""
        last = self._stages[-1]
        return self._with(self._stages[:-1]
                          + (dataclasses.replace(last, where=pred),))

    def next(self, name: str) -> "Pattern":
        """STRICT contiguity: the key's immediately-next event."""
        return self._with(self._stages
                          + (_Stage(name, None, strict=True),))

    def followed_by(self, name: str) -> "Pattern":
        """RELAXED contiguity: later event, intervening ones skipped."""
        return self._with(self._stages
                          + (_Stage(name, None, strict=False),))

    # -- negation (ref: Pattern.notNext / Pattern.notFollowedBy) --------

    def not_next(self, name: str) -> "Pattern":
        """STRICT negation: the key's immediately-next event must NOT
        match this stage's where(). A match kills the partial (the
        killing event re-tests stage 0); any other event satisfies the
        constraint and is immediately re-tested against the following
        stage. Cannot end a pattern (there is no 'next event' deadline
        at the tail — use not_followed_by(...).within(ms))."""
        return self._with(self._stages
                          + (_Stage(name, None, strict=True,
                                    negated=True),))

    def not_followed_by(self, name: str) -> "Pattern":
        """RELAXED negation: NO event matching this stage's where()
        may occur strictly between the surrounding stages. An event
        matching both the forbidden predicate and the FOLLOWING stage
        counts as the following stage. As the LAST stage it needs
        ``within(ms)``: the absence window — the match completes when
        event time passes ``match_start + within`` with no forbidden
        event, ``match_end`` is that deadline, and the stage's
        ``<name>_ts`` column reads -1."""
        return self._with(self._stages
                          + (_Stage(name, None, strict=False,
                                    negated=True),))

    def within(self, ms: int) -> "Pattern":
        return Pattern(self._stages, int(ms), self.after_match_mode,
                       self.after_match_stage)

    def after_match(self, mode: str,
                    stage_name: Optional[str] = None) -> "Pattern":
        """After-match skip strategy (ref: cep/nfa/aftermatch/
        AfterMatchSkipStrategy): SKIP_PAST_LAST_EVENT (default —
        deterministic, each event in at most one match); NO_SKIP
        (the reference default — overlapping matches enumerated from a
        BOUNDED per-key partial buffer, cap + loud overflow; linear
        patterns only — quantifiers with NO_SKIP are refused at build
        because the branch enumeration is exactly the exponential
        SharedBuffer this design trades away); SKIP_TO_FIRST /
        SKIP_TO_LAST (``stage_name`` required) — same multi-partial
        engine, but each completed match prunes every partial whose
        start precedes the first/last event the match mapped to that
        stage (a ``times(n)`` stage resolves to its ``<name>_1`` /
        ``<name>_n`` expansion)."""
        modes = ("SKIP_PAST_LAST_EVENT", "NO_SKIP",
                 "SKIP_TO_FIRST", "SKIP_TO_LAST")
        if mode not in modes:
            raise ValueError(
                f"after_match mode {mode!r}: supported modes are "
                + ", ".join(modes))
        if mode in ("SKIP_TO_FIRST", "SKIP_TO_LAST"):
            if stage_name is None:
                raise ValueError(
                    f"after_match({mode!r}) needs the stage name the "
                    "skip anchors to: after_match(mode, 'stage')")
        elif stage_name is not None:
            raise ValueError(
                f"after_match({mode!r}) takes no stage name")
        return Pattern(self._stages, self.within_ms, mode, stage_name)

    # -- quantifiers (ref: cep/pattern/Quantifier.java) -----------------

    def times(self, n: int) -> "Pattern":
        """The most recent stage must occur exactly ``n`` times.
        Repetitions inherit the stage's contiguity (next → strict
        consecutive runs; followed_by → gaps allowed) and expand into
        ``n`` engine stages at build time, so the vectorized rank-step
        engine runs them unchanged. Match rows carry
        ``<name>_1_ts .. <name>_n_ts``."""
        if n < 1:
            raise ValueError(f"times({n}): n must be >= 1")
        last = self._stages[-1]
        if last.loop or last.optional:
            raise ValueError(
                f"stage {last.name!r} already has a quantifier")
        if last.negated:
            raise ValueError(
                f"negated stage {last.name!r} cannot be quantified")
        return self._with(self._stages[:-1]
                          + (dataclasses.replace(last, times=n),))

    def one_or_more(self) -> "Pattern":
        """GREEDY unbounded repetition of the most recent stage
        (ref: Pattern.oneOrMore, greedy + relaxed internal contiguity).
        Deterministic subset: the loop absorbs every matching event
        until an event matches the FOLLOWING stage (which terminates
        the match), so the pattern must continue past it — a trailing
        oneOrMore would need the reference's exponential partial-match
        buffers to decide when to emit. Match rows carry
        ``<name>_ts`` (first), ``<name>_last_ts`` and ``<name>_count``."""
        last = self._stages[-1]
        if last.strict:
            raise ValueError(
                "one_or_more() requires relaxed contiguity — use "
                "followed_by(), not next(), for the repeated stage")
        if last.times != 1 or last.optional:
            raise ValueError(
                f"stage {last.name!r} already has a quantifier")
        if last.negated:
            raise ValueError(
                f"negated stage {last.name!r} cannot be quantified")
        return self._with(self._stages[:-1]
                          + (dataclasses.replace(last, loop=True),))

    def optional(self) -> "Pattern":
        """The most recent stage may be absent: when an event matches
        the FOLLOWING stage while this one is pending, the automaton
        skips it (ref: Pattern.optional). Its ``<name>_ts`` column is
        -1 in matches where it was skipped."""
        last = self._stages[-1]
        if last.loop or last.times != 1:
            raise ValueError(
                f"stage {last.name!r} already has a quantifier")
        if last.negated:
            raise ValueError(
                f"negated stage {last.name!r} cannot be quantified")
        return self._with(self._stages[:-1]
                          + (dataclasses.replace(last, optional=True),))

    @property
    def stages(self) -> Tuple[_Stage, ...]:
        """Quantifier-EXPANDED engine stages + validation."""
        for s in self._stages:
            if s.where is None:
                raise ValueError(f"stage {s.name!r} has no where()")
        out: List[_Stage] = []
        for i, s in enumerate(self._stages):
            is_last = i == len(self._stages) - 1
            if s.loop and is_last:
                raise ValueError(
                    "a trailing one_or_more() cannot decide when the "
                    "match ends in the deterministic engine — add a "
                    "terminating stage after it")
            if s.optional and is_last:
                raise ValueError(
                    "a trailing optional() stage is not supported — "
                    "the match would be ambiguous (with-or-without)")
            if s.optional and i == 0:
                raise ValueError(
                    "optional() on the first stage is not supported — "
                    "the match start would be undefined when skipped")
            if s.negated and i == 0:
                raise ValueError(
                    "a pattern cannot begin with a negation — the "
                    "match start would be undefined (ref refuses "
                    "notFollowedBy as the first pattern too)")
            if s.negated and s.strict and is_last:
                raise ValueError(
                    "a trailing not_next() is not supported — there is "
                    "no 'next event' to wait for at the tail; use "
                    "not_followed_by(...) with within(ms) for an "
                    "absence window")
            if s.negated and not s.strict and is_last \
                    and self.within_ms is None:
                raise ValueError(
                    "a trailing not_followed_by() needs within(ms) — "
                    "the absence window that decides when 'it never "
                    "came' becomes a match")
            if s.negated and self._stages[i - 1].negated:
                raise ValueError(
                    "adjacent negated stages are not supported — merge "
                    "the forbidden predicates into one negated stage")
            if s.negated and (self._stages[i - 1].loop
                              or self._stages[i - 1].optional):
                raise ValueError(
                    f"negated stage {s.name!r} directly after a "
                    "quantified stage is not supported (the quantifier "
                    "exit would have to test the forbidden predicate)")
            if s.negated and not is_last and self._stages[i + 1].strict:
                raise ValueError(
                    f"stage after negated {s.name!r} must use "
                    "followed_by() (the negated stage consumes no "
                    "event, so strict next() contiguity is undefined)")
            if (s.loop or s.optional) and not is_last \
                    and self._stages[i + 1].strict:
                raise ValueError(
                    f"stage after quantified {s.name!r} must use "
                    "followed_by() (strict next() after a variable-"
                    "length stage is ambiguous)")
            if s.times == 1:
                out.append(s)
            else:
                for rep in range(1, s.times + 1):
                    out.append(dataclasses.replace(
                        s, name=f"{s.name}_{rep}", times=1,
                        # first repetition keeps the stage's contiguity
                        # vs its predecessor; the rest repeat with the
                        # stage's own contiguity between repetitions
                        strict=s.strict))
        if sum(1 for s in out if s.loop) > 1:
            raise ValueError(
                "at most one one_or_more() stage per pattern (the "
                "engine keeps a single loop counter per key)")
        return tuple(out)


class CepOperator:
    """Keyed pattern-matching operator (ref: cep/operator/CepOperator).
    Driver protocol mirrors KeyedProcessOperator: process_batch ingests,
    take_fired returns match rows."""

    def __init__(self, pattern: Pattern, *, num_shards: int = 128,
                 slots_per_shard: int = 1024) -> None:
        self.pattern = pattern
        self.stages = pattern.stages
        self.S = len(self.stages)
        if self.S < 1:
            raise ValueError("pattern needs at least one stage")
        self.directory = KeyDirectory(num_shards, slots_per_shard)
        cap = num_shards * slots_per_shard
        self.stage = np.zeros(cap, np.int32)        # next stage to match
        self.stage_ts = np.zeros((cap, self.S), np.int64)
        # quantifier flags over EXPANDED stages + loop state (at most
        # one one_or_more stage per pattern — validated at build)
        self._is_loop = np.array([s.loop for s in self.stages], bool)
        self._is_opt = np.array([s.optional for s in self.stages], bool)
        self._is_neg = np.array([s.negated for s in self.stages], bool)
        # trailing relaxed negation = absence pattern: a partial parked
        # at stage S-1 completes when event time passes
        # match_start + within with no forbidden event (build validated
        # within is set and the stage is relaxed)
        self._trail_neg = bool(self._is_neg[-1])
        self._loop_idx = (int(np.nonzero(self._is_loop)[0][0])
                          if self._is_loop.any() else -1)
        self.loop_cnt = np.zeros(cap, np.int32)
        self.loop_last = np.zeros(cap, np.int64)
        # highest event ts processed per key: the automaton consumes
        # each key's events in time order WITHIN a batch; an event
        # arriving in a later batch but timestamped before this frontier
        # cannot be sequenced (no cross-batch buffering in v1) — it is
        # dropped WITH accounting (late_records), never silently woven
        # in out of order (which could emit matches whose stage
        # timestamps run backward)
        self._last_ts = np.full(cap, np.iinfo(np.int64).min, np.int64)
        self.watermark = LONG_MIN
        self.late_records = 0
        self.records_dropped_full = 0
        self.state_version = 0
        self._matches: List[Dict[str, np.ndarray]] = []
        # NO_SKIP / SKIP_TO_FIRST / SKIP_TO_LAST: a BOUNDED
        # partial-match buffer per key — the SharedBuffer role (ref:
        # cep/nfa/sharedbuffer) capped at ``max_partials`` columns with
        # loud overflow. Linear patterns only: quantifiers would need
        # branch enumeration (the exponential part this design
        # refuses). ``no_skip`` names the ENGINE (multi-partial) — the
        # skip-to modes run on it with post-completion pruning.
        mode = pattern.after_match_mode
        self.no_skip = mode in ("NO_SKIP", "SKIP_TO_FIRST",
                                "SKIP_TO_LAST")
        self.max_partials = 8
        # SKIP_TO_FIRST/LAST anchor: index (in EXPANDED stages) of the
        # referenced stage — FIRST takes the earliest expansion
        # (<name>_1), LAST the latest (<name>_n)
        self._skip_ref: Optional[int] = None
        if mode in ("SKIP_TO_FIRST", "SKIP_TO_LAST"):
            ref_name = pattern.after_match_stage
            cands = [i for i, s in enumerate(self.stages)
                     if s.name == ref_name
                     or (s.name.rsplit("_", 1)[0] == ref_name
                         and s.name.rsplit("_", 1)[-1].isdigit())]
            if not cands:
                raise ValueError(
                    f"after_match({mode!r}, {ref_name!r}): no stage "
                    f"named {ref_name!r} (stages: "
                    f"{[s.name for s in self.stages]})")
            self._skip_ref = (cands[0] if mode == "SKIP_TO_FIRST"
                              else cands[-1])
        if self.no_skip:
            if self._is_loop.any() or self._is_opt.any():
                raise NotImplementedError(
                    f"after_match({mode!r}) supports linear patterns "
                    "(next/followed_by/times) only; one_or_more and "
                    "optional need the exponential branch enumeration "
                    "of the reference's SharedBuffer — use the default "
                    "SKIP_PAST_LAST_EVENT for quantified patterns")
            if self._is_neg.any():
                raise NotImplementedError(
                    f"after_match({mode!r}) does not support negated "
                    "stages — negation runs on the default "
                    "single-partial engine (SKIP_PAST_LAST_EVENT)")
            P = self.max_partials
            self.p_stage = np.full((cap, P), -1, np.int8)
            self.p_ts = np.zeros((cap, P, self.S), np.int64)

    # -- data plane ------------------------------------------------------

    def process_batch(self, keys, ts, data: Dict[str, np.ndarray],
                      valid=None) -> None:
        self.state_version += 1
        keys = np.asarray(keys, np.int64)
        ts = np.asarray(ts, np.int64)
        valid = (np.ones(len(ts), bool) if valid is None
                 else np.asarray(valid, bool))
        idx = np.nonzero(valid)[0]
        if len(idx) == 0:
            return
        slots = self.directory.assign(keys[idx])
        bad = slots < 0
        if bad.any():
            account_full_drop(self, int(bad.sum()))
            idx, slots = idx[~bad], slots[~bad]
        if len(idx) == 0:
            return

        # cross-batch order: drop events behind the key's frontier
        fresh = ts[idx] >= self._last_ts[slots]
        if not fresh.all():
            self.late_records += int((~fresh).sum())
            idx, slots = idx[fresh], slots[fresh]
            if len(idx) == 0:
                return

        # pre-evaluate every stage predicate over the whole batch ONCE
        # (vectorized; the rank loop below only gathers bits)
        sub = {k: np.asarray(v)[idx] for k, v in data.items()}
        preds = np.stack([np.asarray(st.where(sub), bool)
                          for st in self.stages])      # (S, n)

        # order by (key, ts); within-key rank = position in its run
        order = np.lexsort((ts[idx], keys[idx]))
        sl = slots[order].astype(np.int64)
        tt = ts[idx][order]
        kk = keys[idx][order]
        pr = preds[:, order]                            # (S, n)
        run_start = np.empty(len(sl), bool)
        run_start[0] = True
        run_start[1:] = kk[1:] != kk[:-1]
        rank = np.arange(len(sl)) - np.maximum.accumulate(
            np.where(run_start, np.arange(len(sl)), 0))
        max_rank = int(rank.max()) + 1

        if self.no_skip:
            self._steps_no_skip(sl, tt, kk, pr, rank, max_rank)
            return

        within = self.pattern.within_ms
        strict = np.array([s.strict for s in self.stages], bool)
        is_loop, is_opt, is_neg = self._is_loop, self._is_opt, self._is_neg
        for r in range(max_rank):
            m = rank == r                    # one event per key this step
            s_r = sl[m]
            t_r = tt[m]
            p_r = pr[:, m]                   # (S, k)
            k = len(s_r)
            ar = np.arange(k)
            cur = self.stage[s_r]            # (k,) next stage to match

            # trailing absence: a partial parked at the negated tail
            # whose deadline (match_start + within) the current event's
            # ts has passed COMPLETES — no forbidden event arrived in
            # the window (events arrive in ts order per key). Must run
            # BEFORE the expiry reset below, which tests the very same
            # age condition. The completing event then starts fresh
            # against stage 0 in this step.
            if self._trail_neg:
                due = ((cur == self.S - 1)
                       & (t_r - self.stage_ts[s_r, 0] > within))
                if due.any():
                    f = np.nonzero(due)[0]
                    self._matches.append(
                        self._absence_rows(s_r[f], kk[m][f]))
                    cur = np.where(due, 0, cur)

            # within-window expiry: partial too old resets to stage 0
            if within is not None:
                expired = (cur > 0) & (t_r - self.stage_ts[s_r, 0] > within)
                cur = np.where(expired, 0, cur)
                if self._loop_idx >= 0:
                    self.loop_cnt[s_r[expired]] = 0

            curc = np.minimum(cur, self.S - 1)
            hit_cur = p_r[curc, ar]
            nxtc = np.minimum(cur + 1, self.S - 1)
            has_next = cur + 1 < self.S
            hit_next = p_r[nxtc, ar] & has_next
            lp = is_loop[curc] & (cur < self.S)
            op_ = is_opt[curc] & (cur < self.S)
            ng = is_neg[curc] & (cur < self.S)
            ng_strict = ng & strict[curc]
            in_loop = lp & (self.loop_cnt[s_r] > 0)

            # decision precedence (greedy loop first):
            # A. loop enter/continue: stay, count, track first/last ts
            a_loop = lp & hit_cur
            # B. loop exit: the FOLLOWING stage's event terminates it
            b_exit = in_loop & ~hit_cur & hit_next
            # C. optional skip: next stage's event while optional pends
            c_skip = op_ & ~hit_cur & hit_next
            # N. negation: the forbidden event KILLS the partial.
            #    not_next: any hit on the immediately-next event kills;
            #    not_followed_by: a hit kills UNLESS the same event
            #    matches the FOLLOWING stage (then no forbidden event
            #    occurred strictly between — the event IS the next
            #    stage). A non-killing event at a negated stage either
            #    passes over it (hit_next → +2; not_next with no next
            #    hit → +1, the constraint is spent on this one event)
            #    or, for relaxed negation, is skipped (stay).
            n_kill = ng & hit_cur & (ng_strict | ~hit_next)
            n_pass2 = ng & ~n_kill & hit_next
            n_pass1 = ng_strict & ~n_kill & ~hit_next
            # D. plain advance
            d_adv = ~lp & ~c_skip & ~ng & hit_cur
            # E. strict miss -> partial dies (breaking event re-tests
            #    stage 0)
            miss_strict = (~a_loop & ~b_exit & ~c_skip & ~d_adv & ~ng
                           & ~hit_cur & strict[curc] & (cur > 0))
            die = miss_strict | n_kill
            restart = die & p_r[0, ar]

            new_stage = np.where(
                a_loop, cur,
                np.where(b_exit | c_skip | n_pass2, cur + 2,
                         np.where(d_adv | n_pass1, cur + 1,
                                  np.where(die,
                                           np.where(restart, 1, 0),
                                           cur))))

            # timestamp bookkeeping
            enter_loop = a_loop & ~in_loop
            if self._loop_idx >= 0:
                self.loop_cnt[s_r[enter_loop]] = 1
                cont = a_loop & in_loop
                self.loop_cnt[s_r[cont]] += 1
                self.loop_last[s_r[a_loop]] = t_r[a_loop]
            # first occurrence of a stage writes its ts: plain advances
            # at cur, loop entries at cur, exits/skips at cur+1
            w_cur = d_adv | enter_loop | restart
            st_cur = np.where(restart, 0, cur)
            self.stage_ts[s_r[w_cur], st_cur[w_cur]] = t_r[w_cur]
            w_nxt = b_exit | c_skip | n_pass2
            self.stage_ts[s_r[w_nxt], np.minimum(cur[w_nxt] + 1,
                                                 self.S - 1)] = t_r[w_nxt]
            # a skipped optional / passed negated stage reads -1 in the
            # match row (the stage consumed no event)
            w_abs = c_skip | n_pass2 | n_pass1
            self.stage_ts[s_r[w_abs], curc[w_abs]] = -1

            done = new_stage >= self.S
            if done.any():
                d = np.nonzero(done)[0]
                row = {"key": kk[m][d],
                       "match_start": self.stage_ts[s_r[d], 0].copy(),
                       "match_end": t_r[d].copy()}
                for si, stg in enumerate(self.stages):
                    row[f"{stg.name}_ts"] = self.stage_ts[s_r[d], si].copy()
                if self._loop_idx >= 0:
                    ln = self.stages[self._loop_idx].name
                    row[f"{ln}_last_ts"] = self.loop_last[s_r[d]].copy()
                    row[f"{ln}_count"] = self.loop_cnt[s_r[d]].copy()
                    self.loop_cnt[s_r[d]] = 0
                self._matches.append(row)
                new_stage = np.where(done, 0, new_stage)  # SKIP_PAST_LAST

            self.stage[s_r] = new_stage.astype(np.int32)
            self._last_ts[s_r] = t_r

    def _absence_rows(self, slots, keys) -> Dict[str, np.ndarray]:
        """Complete trailing-absence partials: the window
        [match_start, match_start + within] closed with no forbidden
        event. Builds the match rows (match_end = the deadline; the
        negated tail's ts column = -1) and resets the partials."""
        within = self.pattern.within_ms
        start = self.stage_ts[slots, 0].copy()
        row = {"key": np.asarray(keys, np.int64).copy(),
               "match_start": start,
               "match_end": start + within}
        for si, stg in enumerate(self.stages):
            row[f"{stg.name}_ts"] = (
                np.full(len(slots), -1, np.int64) if stg.negated
                else self.stage_ts[slots, si].copy())
        if self._loop_idx >= 0:
            ln = self.stages[self._loop_idx].name
            row[f"{ln}_last_ts"] = self.loop_last[slots].copy()
            row[f"{ln}_count"] = self.loop_cnt[slots].copy()
            self.loop_cnt[slots] = 0
        self.stage[slots] = 0
        return row

    def _steps_no_skip(self, sl, tt, kk, pr, rank, max_rank) -> None:
        """NO_SKIP rank-step engine: every key advances ALL its live
        partials on each event at once (vectorized over keys × the
        bounded partial axis), and an event matching stage 0 also
        SPAWNS a fresh partial — overlapping matches enumerate across
        partials. Per partial the take is greedy (the operator's
        documented determinism trade); across partials the overlap
        semantics match the reference's NO_SKIP for linear patterns.

        BATCH ATOMICITY: the partial-buffer overflow error must leave
        the operator exactly as it was before the batch — earlier rank
        steps have already advanced partials and appended matches by the
        time a later rank overflows, and a caller that catches the error
        (to fail over through restore, or to drop the batch) must not
        observe half-applied state or double-emitted matches on retry.
        The touched rows (only the batch's key slots) are snapshotted on
        entry and rolled back on the error path — an exact guarantee a
        pre-scan cannot give, since slot liberation (expiry, completion,
        strict death) during the batch feeds back into overflow. One
        deliberate residue: key-directory slots assigned for the batch's
        new keys (in process_batch, before this point) stay assigned —
        the key→slot mapping is idempotent and carries no match state,
        the slot is reused if the key returns, and a restore-from-
        checkpoint rebuilds the directory anyway; only keys never seen
        again leave an empty slot behind."""
        touched = np.unique(sl)
        bak = (self.p_stage[touched].copy(), self.p_ts[touched].copy(),
               self._last_ts[touched].copy(), len(self._matches))
        try:
            self._steps_no_skip_inner(sl, tt, kk, pr, rank, max_rank)
        except Exception:
            self.p_stage[touched], self.p_ts[touched] = bak[0], bak[1]
            self._last_ts[touched] = bak[2]
            del self._matches[bak[3]:]
            raise

    def _steps_no_skip_inner(self, sl, tt, kk, pr, rank,
                             max_rank) -> None:
        S, P = self.S, self.max_partials
        within = self.pattern.within_ms
        strict = np.array([s.strict for s in self.stages], bool)
        for r in range(max_rank):
            m = rank == r
            s_r = sl[m]
            t_r = tt[m]
            p_r = pr[:, m]                     # (S, k)
            k = len(s_r)
            ar = np.arange(k)
            st = self.p_stage[s_r].astype(np.int32)   # (k, P)
            act = st >= 0
            if within is not None and act.any():
                exp = act & (t_r[:, None] - self.p_ts[s_r, :, 0] > within)
                st = np.where(exp, -1, st)
                act = st >= 0
            stc = np.clip(st, 0, S - 1)
            hit = p_r.T[ar[:, None], stc] & act       # (k, P)
            died = act & ~hit & strict[stc] & (st > 0)
            adv = act & hit
            ii, pp = np.nonzero(adv)
            if len(ii):
                self.p_ts[s_r[ii], pp, stc[ii, pp]] = t_r[ii]
            st = np.where(adv, st + 1, np.where(died, -1, st))
            compl = st >= S
            if compl.any():
                ci, cp = np.nonzero(compl)
                if self._skip_ref is None:
                    # NO_SKIP: every completion emits
                    row = {"key": kk[m][ci],
                           "match_start": self.p_ts[s_r[ci], cp, 0].copy(),
                           "match_end": t_r[ci].copy()}
                    for si, stg in enumerate(self.stages):
                        row[f"{stg.name}_ts"] = self.p_ts[
                            s_r[ci], cp, si].copy()
                    self._matches.append(row)
                else:
                    # SKIP_TO_FIRST/LAST: per key, completions resolve
                    # in ascending match_start; each emitted match
                    # prunes every partial — and every not-yet-emitted
                    # completion — whose start precedes the ts of the
                    # event it mapped to the referenced stage.
                    # Completions are rare; this stays scalar.
                    ref = self._skip_ref
                    for i in np.unique(ci):
                        pps = cp[ci == i]
                        starts = self.p_ts[s_r[i], pps, 0]
                        cut = None
                        for p in pps[np.argsort(starts, kind="stable")]:
                            if cut is not None \
                                    and self.p_ts[s_r[i], p, 0] < cut:
                                continue  # pruned by an earlier match
                            row = {
                                "key": kk[m][[i]].copy(),
                                "match_start": self.p_ts[
                                    s_r[i], p, [0]].copy(),
                                "match_end": t_r[[i]].copy()}
                            for si, stg in enumerate(self.stages):
                                row[f"{stg.name}_ts"] = self.p_ts[
                                    s_r[i], p, [si]].copy()
                            self._matches.append(row)
                            cut = int(self.p_ts[s_r[i], p, ref])
                        live = (st[i] >= 0) & (st[i] < S)
                        st[i, live
                           & (self.p_ts[s_r[i], :, 0] < cut)] = -1
                st = np.where(compl, -1, st)
            # spawn: stage-0 match starts a NEW partial (even when the
            # same event extended others — the overlap contract)
            want = p_r[0]
            if want.any():
                free = st < 0
                has_free = free.any(axis=1)
                over = want & ~has_free
                if over.any():
                    raise RuntimeError(
                        f"CEP NO_SKIP partial-buffer overflow: a key "
                        f"exceeded {P} simultaneous partial matches "
                        "(cep max_partials); narrow the begin-stage "
                        "predicate, add within(), or use "
                        "SKIP_PAST_LAST_EVENT")
                ff = np.argmax(free, axis=1)
                wi = np.nonzero(want)[0]
                if S == 1:
                    self._matches.append({
                        "key": kk[m][wi],
                        "match_start": t_r[wi].copy(),
                        "match_end": t_r[wi].copy(),
                        f"{self.stages[0].name}_ts": t_r[wi].copy()})
                else:
                    st[wi, ff[wi]] = 1
                    self.p_ts[s_r[wi], ff[wi], 0] = t_r[wi]
            self.p_stage[s_r] = st.astype(np.int8)
            self._last_ts[s_r] = t_r

    def take_fired(self):
        from flink_tpu.ops.window import FiredWindows

        if not self._matches:
            return None
        parts = self._matches
        self._matches = []
        out = {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}
        out["__ts__"] = out["match_end"].astype(np.int64)
        return FiredWindows(data=out)

    # -- time plane ------------------------------------------------------

    def advance_watermark(self, wm: int):
        from flink_tpu.ops.window import FiredWindows

        if wm > self.watermark:
            self.watermark = wm
        # trailing absence: the watermark passing a pending partial's
        # deadline PROVES no forbidden event with ts <= deadline is
        # still coming — the match completes on time progress alone
        # (the in-stream path in process_batch only helps keys that
        # keep receiving events)
        if self._trail_neg and self.watermark != LONG_MIN:
            within = self.pattern.within_ms
            pend = self.stage == self.S - 1
            due = pend & (self.stage_ts[:, 0] + within <= self.watermark)
            if due.any():
                self.state_version += 1
                slots = np.nonzero(due)[0]
                keys = self.directory.key_of_slots(slots)
                row = self._absence_rows(slots, keys)
                row["__ts__"] = row["match_end"].astype(np.int64).copy()
                return FiredWindows(data=row)
        return FiredWindows(data={"__ts__": np.zeros(0, np.int64)})

    def final_watermark(self) -> int:
        base = self.watermark if self.watermark != LONG_MIN else 0
        if self._trail_neg:
            # flush every pending absence window at end of input
            pend = self.stage == self.S - 1
            if pend.any():
                base = max(base, int(self.stage_ts[pend, 0].max())
                           + self.pattern.within_ms)
        return base

    def quiesce(self) -> None:
        pass

    def throttle(self) -> None:
        pass

    # -- snapshot seam ----------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "kind": "cep",
            "directory": self.directory.snapshot(),
            "stage": self.stage.copy(),
            "stage_ts": self.stage_ts.copy(),
            "loop_cnt": self.loop_cnt.copy(),
            "loop_last": self.loop_last.copy(),
            "watermark": self.watermark,
            "late_records": self.late_records,
            "records_dropped_full": self.records_dropped_full,
            "last_ts": self._last_ts.copy(),
            "p_stage": (self.p_stage.copy() if self.no_skip else None),
            "p_ts": (self.p_ts.copy() if self.no_skip else None),
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.directory = KeyDirectory.restore(
            self.directory.num_shards, self.directory.slots_per_shard,
            snap["directory"],
            (self.directory.shard_lo, self.directory.shard_hi))
        self.stage = np.array(snap["stage"])
        self.stage_ts = np.array(snap["stage_ts"])
        if snap.get("loop_cnt") is not None:
            self.loop_cnt = np.array(snap["loop_cnt"])
            self.loop_last = np.array(snap["loop_last"])
        self.watermark = snap["watermark"]
        self.late_records = snap["late_records"]
        self.records_dropped_full = snap["records_dropped_full"]
        self._last_ts = np.array(snap["last_ts"])
        if self.no_skip and snap.get("p_stage") is not None:
            self.p_stage = np.array(snap["p_stage"])
            self.p_ts = np.array(snap["p_ts"])
        self._matches = []


class CEP:
    """Entry point (ref: cep/CEP.java): ``CEP.pattern(keyed_stream,
    pattern)`` → DataStream of match rows."""

    @staticmethod
    def pattern(keyed_stream, pattern: Pattern, name: str = "cep"):
        from flink_tpu.graph.transformations import CepTransformation

        kt = keyed_stream.transform
        t = CepTransformation(name, (kt,), pattern=pattern,
                              key_field=kt.key_field)
        keyed_stream.env._register(t)
        from flink_tpu.api.datastream import DataStream

        return DataStream(keyed_stream.env, t)
