"""CEP — complex event processing (pattern matching on keyed streams).

ref: flink-libraries/flink-cep (Pattern.begin/next/followedBy/where/
within → NFACompiler → CepOperator keeping per-key NFA state +
partial-match buffers in keyed state).

TPU-first redesign: the reference walks one NFA per key per RECORD.
Here the per-key automaton state is COLUMNS over key slots (current
stage, window-start ts, per-stage match timestamps), and a microbatch
is processed by WITHIN-KEY RANK: sort by (key, ts), then step r
advances EVERY key's automaton on its r-th event of the batch at once —
the sequential dependence lives only along each key's own event chain,
so the loop length is the longest per-key run in the batch while each
step is one vectorized transition over all keys.

Supported semantics (a deterministic, documented subset of the
reference's full NFA):
- linear patterns: ``begin(a).next(b)`` (STRICT contiguity — the very
  next event of that key must match or the partial resets) and
  ``followed_by`` (RELAXED — non-matching events in between are
  skipped), with vectorized ``where`` predicates per stage;
- ``within(ms)``: a partial older than the window resets (the event
  that broke it may immediately start a new partial);
- after-match skipping: SKIP_PAST_LAST_EVENT — each event belongs to
  at most one match, matches never overlap (deterministic; the
  reference's default NO_SKIP enumerates overlapping matches, which
  requires the exponential partial-match buffers this design
  deliberately trades away);
- one active partial per key (greedy earliest): no simultaneous
  alternative partials. A failed strict transition re-tests the
  breaking event against stage 0.

Matches emit one row per completed pattern: key, ``<stage>_ts`` per
stage, and the match's start/end timestamps.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.state.keyed import KeyDirectory, account_full_drop
from flink_tpu.time.watermarks import LONG_MIN


@dataclasses.dataclass(frozen=True)
class _Stage:
    name: str
    where: Optional[Callable[[Dict[str, np.ndarray]], np.ndarray]]
    strict: bool  # True = next() contiguity; False = followed_by()


class Pattern:
    """Fluent pattern builder (ref: cep/pattern/Pattern.java)."""

    def __init__(self, stages: Tuple[_Stage, ...],
                 within_ms: Optional[int] = None):
        self._stages = stages
        self.within_ms = within_ms

    @classmethod
    def begin(cls, name: str) -> "Pattern":
        return cls((_Stage(name, None, strict=False),))

    def where(self, pred: Callable[[Dict[str, np.ndarray]], np.ndarray]) -> "Pattern":
        """Vectorized predicate over the batch's field arrays → (B,)
        bool. Applies to the most recent stage."""
        last = self._stages[-1]
        return Pattern(self._stages[:-1]
                       + (_Stage(last.name, pred, last.strict),),
                       self.within_ms)

    def next(self, name: str) -> "Pattern":
        """STRICT contiguity: the key's immediately-next event."""
        return Pattern(self._stages + (_Stage(name, None, strict=True),),
                       self.within_ms)

    def followed_by(self, name: str) -> "Pattern":
        """RELAXED contiguity: later event, intervening ones skipped."""
        return Pattern(self._stages + (_Stage(name, None, strict=False),),
                       self.within_ms)

    def within(self, ms: int) -> "Pattern":
        return Pattern(self._stages, int(ms))

    @property
    def stages(self) -> Tuple[_Stage, ...]:
        for s in self._stages:
            if s.where is None:
                raise ValueError(f"stage {s.name!r} has no where()")
        return self._stages


class CepOperator:
    """Keyed pattern-matching operator (ref: cep/operator/CepOperator).
    Driver protocol mirrors KeyedProcessOperator: process_batch ingests,
    take_fired returns match rows."""

    def __init__(self, pattern: Pattern, *, num_shards: int = 128,
                 slots_per_shard: int = 1024) -> None:
        self.pattern = pattern
        self.stages = pattern.stages
        self.S = len(self.stages)
        if self.S < 1:
            raise ValueError("pattern needs at least one stage")
        self.directory = KeyDirectory(num_shards, slots_per_shard)
        cap = num_shards * slots_per_shard
        self.stage = np.zeros(cap, np.int32)        # next stage to match
        self.stage_ts = np.zeros((cap, self.S), np.int64)
        # highest event ts processed per key: the automaton consumes
        # each key's events in time order WITHIN a batch; an event
        # arriving in a later batch but timestamped before this frontier
        # cannot be sequenced (no cross-batch buffering in v1) — it is
        # dropped WITH accounting (late_records), never silently woven
        # in out of order (which could emit matches whose stage
        # timestamps run backward)
        self._last_ts = np.full(cap, np.iinfo(np.int64).min, np.int64)
        self.watermark = LONG_MIN
        self.late_records = 0
        self.records_dropped_full = 0
        self.state_version = 0
        self._matches: List[Dict[str, np.ndarray]] = []

    # -- data plane ------------------------------------------------------

    def process_batch(self, keys, ts, data: Dict[str, np.ndarray],
                      valid=None) -> None:
        self.state_version += 1
        keys = np.asarray(keys, np.int64)
        ts = np.asarray(ts, np.int64)
        valid = (np.ones(len(ts), bool) if valid is None
                 else np.asarray(valid, bool))
        idx = np.nonzero(valid)[0]
        if len(idx) == 0:
            return
        slots = self.directory.assign(keys[idx])
        bad = slots < 0
        if bad.any():
            account_full_drop(self, int(bad.sum()))
            idx, slots = idx[~bad], slots[~bad]
        if len(idx) == 0:
            return

        # cross-batch order: drop events behind the key's frontier
        fresh = ts[idx] >= self._last_ts[slots]
        if not fresh.all():
            self.late_records += int((~fresh).sum())
            idx, slots = idx[fresh], slots[fresh]
            if len(idx) == 0:
                return

        # pre-evaluate every stage predicate over the whole batch ONCE
        # (vectorized; the rank loop below only gathers bits)
        sub = {k: np.asarray(v)[idx] for k, v in data.items()}
        preds = np.stack([np.asarray(st.where(sub), bool)
                          for st in self.stages])      # (S, n)

        # order by (key, ts); within-key rank = position in its run
        order = np.lexsort((ts[idx], keys[idx]))
        sl = slots[order].astype(np.int64)
        tt = ts[idx][order]
        kk = keys[idx][order]
        pr = preds[:, order]                            # (S, n)
        run_start = np.empty(len(sl), bool)
        run_start[0] = True
        run_start[1:] = kk[1:] != kk[:-1]
        rank = np.arange(len(sl)) - np.maximum.accumulate(
            np.where(run_start, np.arange(len(sl)), 0))
        max_rank = int(rank.max()) + 1

        within = self.pattern.within_ms
        strict = np.array([s.strict for s in self.stages], bool)
        for r in range(max_rank):
            m = rank == r                    # one event per key this step
            s_r = sl[m]
            t_r = tt[m]
            p_r = pr[:, m]                   # (S, k)
            cur = self.stage[s_r]            # (k,) next stage to match

            # within-window expiry: partial too old resets to stage 0
            if within is not None:
                expired = (cur > 0) & (t_r - self.stage_ts[s_r, 0] > within)
                cur = np.where(expired, 0, cur)

            hit = p_r[np.minimum(cur, self.S - 1), np.arange(len(s_r))]
            adv = hit                        # advance on match
            # strict stage missed -> partial dies; the breaking event
            # re-tests against stage 0
            miss_strict = ~hit & strict[np.minimum(cur, self.S - 1)] & (cur > 0)
            restart = miss_strict & p_r[0, np.arange(len(s_r))]
            new_stage = np.where(adv, cur + 1,
                                 np.where(miss_strict,
                                          np.where(restart, 1, 0), cur))
            # record the matched stage's timestamp
            st_idx = np.where(adv, cur, 0)
            write = adv | restart
            self.stage_ts[s_r[write], st_idx[write]] = t_r[write]

            done = new_stage >= self.S
            if done.any():
                d = np.nonzero(done)[0]
                row = {"key": kk[m][d],
                       "match_start": self.stage_ts[s_r[d], 0].copy(),
                       "match_end": t_r[d].copy()}
                for si, stg in enumerate(self.stages[:-1]):
                    row[f"{stg.name}_ts"] = self.stage_ts[s_r[d], si].copy()
                row[f"{self.stages[-1].name}_ts"] = t_r[d].copy()
                self._matches.append(row)
                new_stage = np.where(done, 0, new_stage)  # SKIP_PAST_LAST

            self.stage[s_r] = new_stage.astype(np.int32)
            self._last_ts[s_r] = t_r

    def take_fired(self):
        from flink_tpu.ops.window import FiredWindows

        if not self._matches:
            return None
        parts = self._matches
        self._matches = []
        out = {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}
        out["__ts__"] = out["match_end"].astype(np.int64)
        return FiredWindows(data=out)

    # -- time plane ------------------------------------------------------

    def advance_watermark(self, wm: int):
        from flink_tpu.ops.window import FiredWindows

        if wm > self.watermark:
            self.watermark = wm
        return FiredWindows(data={"__ts__": np.zeros(0, np.int64)})

    def final_watermark(self) -> int:
        return self.watermark if self.watermark != LONG_MIN else 0

    def quiesce(self) -> None:
        pass

    def throttle(self) -> None:
        pass

    # -- snapshot seam ----------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "kind": "cep",
            "directory": self.directory.snapshot(),
            "stage": self.stage.copy(),
            "stage_ts": self.stage_ts.copy(),
            "watermark": self.watermark,
            "late_records": self.late_records,
            "records_dropped_full": self.records_dropped_full,
            "last_ts": self._last_ts.copy(),
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.directory = KeyDirectory.restore(
            self.directory.num_shards, self.directory.slots_per_shard,
            snap["directory"],
            (self.directory.shard_lo, self.directory.shard_hi))
        self.stage = np.array(snap["stage"])
        self.stage_ts = np.array(snap["stage_ts"])
        self.watermark = snap["watermark"]
        self.late_records = snap["late_records"]
        self.records_dropped_full = snap["records_dropped_full"]
        self._last_ts = np.array(snap["last_ts"])
        self._matches = []


class CEP:
    """Entry point (ref: cep/CEP.java): ``CEP.pattern(keyed_stream,
    pattern)`` → DataStream of match rows."""

    @staticmethod
    def pattern(keyed_stream, pattern: Pattern, name: str = "cep"):
        from flink_tpu.graph.transformations import CepTransformation

        kt = keyed_stream.transform
        t = CepTransformation(name, (kt,), pattern=pattern,
                              key_field=kt.key_field)
        keyed_stream.env._register(t)
        from flink_tpu.api.datastream import DataStream

        return DataStream(keyed_stream.env, t)
