"""``python -m flink_tpu`` → the CLI frontend (cli.py)."""
import sys

from flink_tpu.cli import main

sys.exit(main())
