"""Metrics — counters, gauges, histograms in a scoped group hierarchy.

ref: flink-metrics/flink-metrics-core/.../metrics/{Metric,Counter,Gauge,
Histogram,Meter,MetricGroup}.java and the registry/reporter split
(runtime/metrics/MetricRegistryImpl.java → flink-metrics-prometheus).

The canonical task metrics mirrored from TaskIOMetricGroup:
numRecordsIn/Out, numLateRecordsDropped, busyTimeMsPerSecond,
watermarkLag — plus TPU-first ones the driver feeds: events/sec/chip,
fired windows/advance, device dispatch ms, emit drain backlog.
Export is Prometheus text format (pull via ``MetricsServer`` on
``metrics.port`` or scrape-to-string)."""
from __future__ import annotations

import dataclasses
import http.server
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.config import ConfigOption

METRICS_PORT = ConfigOption(
    "metrics.port", 0,
    "Serve /metrics (Prometheus text) on this port; 0 disables "
    "(ref: flink-metrics-prometheus reporter port).")

METRICS_BIND = ConfigOption(
    "metrics.bind-address", "127.0.0.1",
    "Interface the /metrics endpoint binds; loopback by default (match "
    "the control-plane RpcServer posture) — set 0.0.0.0 to expose.")


# The primitives' WRITE paths are lock-guarded: host-pool worker
# threads (parallel/hostpool.py), the drain thread, and the scrape
# thread all hit one registry, and `self._v += n` / reservoir writes
# are read-modify-write races without it. Reads stay lock-free — a
# scrape observing a value one update stale is fine; losing updates
# is not.
class Counter:
    def __init__(self) -> None:
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    @property
    def value(self) -> int:
        return self._v


class Gauge:
    def __init__(self, fn: Optional[Callable[[], float]] = None) -> None:
        self._fn = fn
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    @property
    def value(self) -> float:
        return self._fn() if self._fn is not None else self._v


class Histogram:
    """Fixed-reservoir histogram (ref: DescriptiveStatisticsHistogram) —
    keeps the last ``size`` samples; quantiles computed on demand."""

    def __init__(self, size: int = 1024) -> None:
        self._buf = np.zeros(size, np.float64)
        self._n = 0
        self._lock = threading.Lock()

    def update(self, v: float) -> None:
        with self._lock:
            self._buf[self._n % len(self._buf)] = v
            self._n += 1

    def _samples(self) -> np.ndarray:
        return self._buf[: min(self._n, len(self._buf))]

    def quantile(self, q: float) -> float:
        s = self._samples()
        return float(np.quantile(s, q)) if len(s) else 0.0

    def quantile_recent(self, q: float, window: int = 32) -> float:
        """Quantile over the newest ``window`` samples — control loops
        (the batch debloater) steer on recent behavior, not the whole
        reservoir's history."""
        n = min(self._n, len(self._buf), window)
        if n == 0:
            return 0.0
        ix = (np.arange(self._n - n, self._n)) % len(self._buf)
        return float(np.quantile(self._buf[ix], q))

    @property
    def count(self) -> int:
        return self._n

    @property
    def mean(self) -> float:
        s = self._samples()
        return float(s.mean()) if len(s) else 0.0


class Meter:
    """Events per second over a sliding minute (ref: MeterView)."""

    def __init__(self) -> None:
        self._events: List[Tuple[float, int]] = []
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        now = time.time()
        with self._lock:
            self._events.append((now, n))
            cut = now - 60
            while self._events and self._events[0][0] < cut:
                self._events.pop(0)

    @property
    def rate(self) -> float:
        with self._lock:  # a concurrent mark() pops the head this reads
            if not self._events:
                return 0.0
            span = max(time.time() - self._events[0][0], 1e-9)
            return sum(n for _, n in self._events) / span


class MetricGroup:
    """Scope-named registry node (ref: MetricGroup addGroup/counter)."""

    def __init__(self, registry: "MetricRegistry", scope: Tuple[str, ...]):
        self._registry = registry
        self._scope = scope

    def add_group(self, name: str) -> "MetricGroup":
        return MetricGroup(self._registry, self._scope + (name,))

    def _register(self, name: str, metric: Any) -> Any:
        self._registry.register(self._scope + (name,), metric)
        return metric

    def counter(self, name: str) -> Counter:
        return self._register(name, Counter())

    def gauge(self, name: str, fn: Optional[Callable[[], float]] = None) -> Gauge:
        return self._register(name, Gauge(fn))

    def histogram(self, name: str, size: int = 1024) -> Histogram:
        return self._register(name, Histogram(size))

    def meter(self, name: str) -> Meter:
        return self._register(name, Meter())


class MetricRegistry:
    def __init__(self) -> None:
        self._metrics: Dict[Tuple[str, ...], Any] = {}

    def group(self, *scope: str) -> MetricGroup:
        return MetricGroup(self, tuple(scope))

    def register(self, scope: Tuple[str, ...], metric: Any) -> None:
        self._metrics[scope] = metric

    def get(self, *scope: str) -> Any:
        return self._metrics.get(tuple(scope))

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for scope, m in self._metrics.items():
            key = ".".join(scope)
            if isinstance(m, Counter):
                out[key] = m.value
            elif isinstance(m, Gauge):
                out[key] = m.value
            elif isinstance(m, Meter):
                out[key] = m.rate
            elif isinstance(m, Histogram):
                out[key + ".p50"] = m.quantile(0.5)
                out[key + ".p90"] = m.quantile(0.9)
                out[key + ".p99"] = m.quantile(0.99)
                out[key + ".max"] = m.quantile(1.0)
                out[key + ".mean"] = m.mean
                out[key + ".count"] = m.count
        return out

    def to_prometheus(self) -> str:
        """Prometheus exposition text (ref: flink-metrics-prometheus
        PrometheusReporter serialization)."""
        lines = []
        for key, v in sorted(self.snapshot().items()):
            name = "flink_tpu_" + key.replace(".", "_").replace("-", "_")
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {float(v)}")
        return "\n".join(lines) + "\n"


class MetricsServer:
    """Minimal /metrics HTTP endpoint (pull model)."""

    def __init__(self, registry: MetricRegistry, port: int,
                 bind: str = "127.0.0.1") -> None:
        reg = registry

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                body = reg.to_prometheus().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence
                pass

        self._httpd = http.server.ThreadingHTTPServer((bind, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
