"""Per-op device profiling seam (``pipeline.profile-dir``).

ref role: the reference's flame-graph/async-profiler integration on the
TaskManager (rest/profiler endpoints) — here the accelerator analogue:
wrap N WARM driver steps in ``jax.profiler.trace`` and reduce the
emitted Chrome-trace events to a per-op device-time summary, so a
"which op costs what" question is answered by measurement instead of
black-box bisection (the PROFILE.md §8.5 mandate: the ~40ms fused-step
composition anomaly did not yield to A/B splitting — only a real
per-op trace can name it).

Two artifacts per profiled run, both under the configured directory:

- the raw ``plugins/profile/<ts>/*.xplane.pb`` + ``*.trace.json.gz``
  TensorBoard/xprof trace (open with xprof for the full timeline);
- ``profile_summary.json`` — the self-contained per-op reduction this
  module computes from the Chrome trace with nothing but stdlib
  (gzip + json): per trace plane (device or host), total/self ms and
  call count per op name, sorted by total time.

Everything here is failure-tolerant by design: profiling must never
take down the job it observes — errors are recorded in the summary,
not raised into the driver loop.
"""
from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import time
from typing import Any, Dict, List, Optional

__all__ = ["StepProfiler", "summarize_trace_dir"]

# host-side python interpreter events (the profiler's own tracing of
# the driver process) start with '$' — noise for a per-OP summary
_PY_EVENT_PREFIX = "$"


def _device_plane(name: str) -> bool:
    """True for planes that carry accelerator op events (the per-op
    answer lives there); host planes are kept in the summary but
    ranked after device planes."""
    n = name.lower()
    return "tpu" in n or "gpu" in n or "device" in n or "/xla" in n


def summarize_trace_dir(trace_dir: str, top: int = 40) -> Dict[str, Any]:
    """Reduce the newest ``*.trace.json.gz`` under ``trace_dir`` to a
    per-op summary: for every trace plane, op name → {total_ms, count},
    device planes first, each plane's ops sorted by total time. Returns
    ``{"error": ...}`` instead of raising when nothing is parseable."""
    pattern = os.path.join(trace_dir, "**", "*.trace.json.gz")
    files = sorted(glob.glob(pattern, recursive=True),
                   key=lambda p: os.path.getmtime(p))
    if not files:
        return {"error": f"no trace.json.gz under {trace_dir!r} — did "
                         "the profiled run dispatch any steps?"}
    try:
        with gzip.open(files[-1], "rt", encoding="utf-8") as f:
            trace = json.load(f)
    except Exception as e:  # noqa: BLE001 — summary must not raise
        return {"error": f"trace parse failed: {type(e).__name__}: {e}"}
    events = trace.get("traceEvents", [])
    plane_names: Dict[Any, str] = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            plane_names[e.get("pid")] = str(
                (e.get("args") or {}).get("name", e.get("pid")))
    # (plane, op) → [total_us, count]
    agg: Dict[Any, Dict[str, List[float]]] = collections.defaultdict(
        lambda: collections.defaultdict(lambda: [0.0, 0]))
    for e in events:
        if e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        if not name or name.startswith(_PY_EVENT_PREFIX):
            continue
        plane = plane_names.get(e.get("pid"), str(e.get("pid")))
        cell = agg[plane][name]
        cell[0] += float(e.get("dur", 0))
        cell[1] += 1
    planes = []
    for plane, ops in agg.items():
        rows = sorted(
            ({"op": op, "total_ms": round(us / 1000.0, 3), "count": n}
             for op, (us, n) in ops.items()),
            key=lambda r: -r["total_ms"])[:top]
        planes.append({
            "plane": plane,
            "device": _device_plane(plane),
            "total_ms": round(
                sum(us for us, _ in ops.values()) / 1000.0, 3),
            "ops": rows,
        })
    planes.sort(key=lambda p: (not p["device"], -p["total_ms"]))
    return {"trace_file": files[-1], "planes": planes}


class StepProfiler:
    """Driver-side trace window: skip ``skip`` warm logical batches,
    trace the next ``steps``, then stop and write
    ``<dir>/profile_summary.json``. ``step()`` is called once per
    logical batch from the ingest loop; ``close()`` (idempotent) stops
    a still-open trace — runs shorter than skip+steps still produce a
    trace of whatever ran inside the window."""

    def __init__(self, trace_dir: str, skip: int = 4,
                 steps: int = 8) -> None:
        self.trace_dir = trace_dir
        self.skip = max(int(skip), 0)
        self.steps = max(int(steps), 1)
        self._seen = 0
        self._active = False
        self._done = False
        self.summary: Optional[Dict[str, Any]] = None
        self.error: Optional[str] = None

    @classmethod
    def from_config(cls, config) -> Optional["StepProfiler"]:
        from flink_tpu.config import PipelineOptions

        d = str(config.get(PipelineOptions.PROFILE_DIR) or "").strip()
        if not d:
            return None
        return cls(d, skip=int(config.get(PipelineOptions.PROFILE_SKIP)),
                   steps=int(config.get(PipelineOptions.PROFILE_STEPS)))

    def step(self) -> None:
        """One logical-batch boundary. Never raises (see module doc)."""
        if self._done:
            return
        self._seen += 1
        try:
            if not self._active and self._seen > self.skip:
                import jax

                os.makedirs(self.trace_dir, exist_ok=True)
                jax.profiler.start_trace(self.trace_dir)
                self._active = True
                self._t0 = time.perf_counter()
            elif self._active and self._seen > self.skip + self.steps:
                self._stop()
        except Exception as e:  # noqa: BLE001
            self.error = f"{type(e).__name__}: {e}"
            self._done = True

    def _stop(self) -> None:
        import jax

        wall = time.perf_counter() - self._t0
        jax.profiler.stop_trace()
        self._active = False
        self._done = True
        self.summary = summarize_trace_dir(self.trace_dir)
        self.summary.setdefault("steps", self.steps)
        self.summary["window_wall_s"] = round(wall, 3)
        try:
            path = os.path.join(self.trace_dir, "profile_summary.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(self.summary, f, indent=2)
            self.summary["summary_file"] = path
        except OSError as e:
            self.summary["error"] = f"summary write failed: {e}"

    def close(self) -> Optional[Dict[str, Any]]:
        """Stop a still-open trace (short runs / failure cleanup) and
        return the summary (None when the window never opened)."""
        if self._active:
            try:
                self._stop()
            except Exception as e:  # noqa: BLE001
                self.error = f"{type(e).__name__}: {e}"
                self._active = False
                self._done = True
        if self.error is not None and self.summary is None:
            return {"error": self.error}
        return self.summary
