"""REST API — the coordinator's HTTP face.

ref: flink-runtime/.../rest/RestServerEndpoint.java and the dispatcher
handlers (JobsOverviewHandler, JobDetailsHandler, JobCancellationHandler,
SavepointTriggerHandler, TaskManagersHandler). Same resource shapes,
backed by the coordinator's RPC methods — one control-plane brain, two
protocols (ref: WebMonitorEndpoint delegating to the DispatcherGateway).

Routes:
    GET    /overview                      cluster summary
    GET    /jobs                          job list
    GET    /jobs/<id>                     job detail (incl. savepoint)
    PATCH  /jobs/<id>?mode=cancel         cancel
    POST   /jobs/<id>/savepoints          trigger a savepoint
    GET    /taskmanagers                  runner list
    GET    /                              minimal HTML overview (Web UI nod)

Binds loopback by default (same rationale as the metrics endpoint:
no unauthenticated control surface on all interfaces by accident).
"""
from __future__ import annotations

import html as html_mod
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse


class RestServer:
    """``target`` is either an RpcServer (preferred: REST calls ride its
    dispatch queue, honoring the single-dispatch-thread discipline) or a
    bare endpoint object — which MUST be internally synchronized, since
    HTTP worker threads then call its rpc_* methods directly
    (JobCoordinator locks internally and qualifies)."""

    def __init__(self, target: Any, port: int = 0,
                 bind: str = "127.0.0.1") -> None:
        if hasattr(target, "dispatch"):
            self._call = target.dispatch
        else:
            self._call = (lambda method, **kw:
                          getattr(target, "rpc_" + method)(**kw))
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _html(self, body: str) -> None:
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:
                code, payload = outer._route("GET", self.path)
                if payload is None:
                    try:
                        self._html(outer._index_html())
                    except Exception as e:  # noqa: BLE001 — HTTP boundary
                        self._send(500, {"error": str(e)})
                else:
                    self._send(code, payload)

            def do_PATCH(self) -> None:
                self._send(*outer._route("PATCH", self.path))

            def do_POST(self) -> None:
                self._send(*outer._route("POST", self.path))

        self._httpd = ThreadingHTTPServer((bind, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    # -- routing ---------------------------------------------------------

    def _route(self, method: str,
               path: str) -> Tuple[int, Optional[Dict[str, Any]]]:
        u = urlparse(path)
        parts = [p for p in u.path.split("/") if p]
        q = parse_qs(u.query)
        try:
            if method == "GET":
                if not parts:
                    return 200, None  # HTML index
                if parts == ["overview"]:
                    runners = self._call("list_runners")
                    jobs = self._call("list_jobs")["jobs"]
                    by_state: Dict[str, int] = {}
                    for j in jobs:
                        by_state[j["state"]] = by_state.get(j["state"], 0) + 1
                    return 200, {
                        "taskmanagers": len(runners),
                        "taskmanagers-alive": sum(
                            1 for r in runners.values() if r["alive"]),
                        "jobs": by_state,
                    }
                if parts == ["jobs"]:
                    return 200, self._call("list_jobs")
                if len(parts) == 2 and parts[0] == "jobs":
                    st = self._call("job_status", job_id=parts[1])
                    if st.get("state") == "UNKNOWN":
                        return 404, {"error": f"no job {parts[1]}"}
                    return 200, {"job_id": parts[1], **st}
                if parts == ["taskmanagers"]:
                    return 200, {"taskmanagers": self._call("list_runners")}
                if parts == ["traces"]:
                    from flink_tpu.obs.tracing import tracer

                    prefix = q.get("name", [""])[0]
                    return 200, {"spans": tracer.spans(prefix)}
                if parts == ["flamegraph"]:
                    from flink_tpu.obs.tracing import sample_threads

                    seconds = min(max(
                        float(q.get("seconds", ["1"])[0]), 0.05), 10.0)
                    hz = min(max(float(q.get("hz", ["50"])[0]), 1.0), 200.0)
                    return 200, sample_threads(seconds, hz)
                return 404, {"error": f"no route {u.path}"}
            if method == "PATCH" and len(parts) == 2 and parts[0] == "jobs":
                mode = q.get("mode", ["cancel"])[0]
                st = self._call("job_status", job_id=parts[1])
                if st.get("state") == "UNKNOWN":
                    return 404, {"error": f"no job {parts[1]}"}
                if mode == "cancel":
                    return 202, self._call("cancel_job", job_id=parts[1])
                if mode == "rescale":
                    # ref: the REST rescale endpoint (PATCH with a new
                    # parallelism) driving the AdaptiveScheduler
                    try:
                        devices = int(q.get("devices", [""])[0])
                    except ValueError:
                        return 400, {"error": "rescale needs devices=N"}
                    resp = self._call("rescale_job", job_id=parts[1],
                                      devices=devices)
                    return (202 if resp.get("ok") else 409), resp
                return 400, {"error": f"unsupported mode {mode!r}"}
            if (method == "POST" and len(parts) == 3 and parts[0] == "jobs"
                    and parts[2] == "savepoints"):
                st = self._call("job_status", job_id=parts[1])
                if st.get("state") == "UNKNOWN":
                    return 404, {"error": f"no job {parts[1]}"}
                resp = self._call("trigger_savepoint", job_id=parts[1])
                return (202 if resp.get("ok") else 409), resp
            return 404, {"error": f"no route {method} {u.path}"}
        except Exception as e:  # noqa: BLE001 — HTTP boundary
            return 500, {"error": str(e)}

    def _index_html(self) -> str:
        esc = html_mod.escape
        jobs = self._call("list_jobs")["jobs"]
        runners = self._call("list_runners")
        rows = "".join(
            f"<tr><td>{esc(str(j['job_id']))}</td><td>{esc(j['state'])}</td>"
            f"<td>{j['attempts']}</td>"
            f"<td>{esc(', '.join(map(str, j['runners'])))}</td></tr>"
            for j in jobs)
        rrows = "".join(
            f"<tr><td>{esc(str(rid))}</td>"
            f"<td>{'alive' if r['alive'] else 'lost'}</td>"
            f"<td>{r['n_devices']}</td></tr>" for rid, r in runners.items())
        return (
            "<html><head><title>flink_tpu</title></head><body>"
            "<h1>flink_tpu cluster</h1>"
            "<h2>Jobs</h2><table border=1><tr><th>id</th><th>state</th>"
            f"<th>attempts</th><th>runners</th></tr>{rows}</table>"
            "<h2>Runners</h2><table border=1><tr><th>id</th><th>status</th>"
            f"<th>devices</th></tr>{rrows}</table>"
            "<p>REST: /overview /jobs /jobs/&lt;id&gt; /taskmanagers</p>"
            "</body></html>")

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
