"""REST API — the coordinator's HTTP face.

ref: flink-runtime/.../rest/RestServerEndpoint.java and the dispatcher
handlers (JobsOverviewHandler, JobDetailsHandler, JobCancellationHandler,
SavepointTriggerHandler, TaskManagersHandler). Same resource shapes,
backed by the coordinator's RPC methods — one control-plane brain, two
protocols (ref: WebMonitorEndpoint delegating to the DispatcherGateway).

Routes:
    GET    /overview                      cluster summary
    GET    /jobs                          job list
    GET    /jobs/<id>                     job detail (incl. savepoint)
    PATCH  /jobs/<id>?mode=cancel         cancel
    POST   /jobs/<id>/savepoints          trigger a savepoint
    GET    /taskmanagers                  runner list
    GET    /                              minimal HTML overview (Web UI nod)

Binds loopback by default (same rationale as the metrics endpoint:
no unauthenticated control surface on all interfaces by accident).
"""
from __future__ import annotations

import html as html_mod
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse


class RestServer:
    """``target`` is either an RpcServer (preferred: REST calls ride its
    dispatch queue, honoring the single-dispatch-thread discipline) or a
    bare endpoint object — which MUST be internally synchronized, since
    HTTP worker threads then call its rpc_* methods directly
    (JobCoordinator locks internally and qualifies)."""

    def __init__(self, target: Any, port: int = 0,
                 bind: str = "127.0.0.1") -> None:
        if hasattr(target, "dispatch"):
            self._call = target.dispatch
        else:
            self._call = (lambda method, **kw:
                          getattr(target, "rpc_" + method)(**kw))
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _html(self, body: str) -> None:
                data = body.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self) -> None:
                code, payload = outer._route("GET", self.path)
                if payload is None:
                    try:
                        self._html(outer._index_html())
                    except Exception as e:  # noqa: BLE001 — HTTP boundary
                        self._send(500, {"error": str(e)})
                else:
                    self._send(code, payload)

            def do_PATCH(self) -> None:
                self._send(*outer._route("PATCH", self.path))

            def do_POST(self) -> None:
                self._send(*outer._route("POST", self.path))

        self._httpd = ThreadingHTTPServer((bind, port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    # -- routing ---------------------------------------------------------

    def _route(self, method: str,
               path: str) -> Tuple[int, Optional[Dict[str, Any]]]:
        u = urlparse(path)
        parts = [p for p in u.path.split("/") if p]
        q = parse_qs(u.query)
        try:
            if method == "GET":
                if not parts:
                    return 200, None  # HTML index
                if parts == ["overview"]:
                    runners = self._call("list_runners")
                    jobs = self._call("list_jobs")["jobs"]
                    by_state: Dict[str, int] = {}
                    for j in jobs:
                        by_state[j["state"]] = by_state.get(j["state"], 0) + 1
                    return 200, {
                        "taskmanagers": len(runners),
                        "taskmanagers-alive": sum(
                            1 for r in runners.values() if r["alive"]),
                        "jobs": by_state,
                    }
                if parts == ["jobs"]:
                    return 200, self._call("list_jobs")
                if len(parts) == 2 and parts[0] == "jobs":
                    st = self._call("job_status", job_id=parts[1])
                    if st.get("state") == "UNKNOWN":
                        return 404, {"error": f"no job {parts[1]}"}
                    return 200, {"job_id": parts[1], **st}
                if (len(parts) == 3 and parts[0] == "jobs"
                        and parts[2] == "graph"):
                    # physical DAG + live metrics for the web UI (ref:
                    # the REST job vertices/backpressure endpoints)
                    g = self._call("execution_graph", job_id=parts[1])
                    if not g.get("found"):
                        return 404, {"error": f"no job {parts[1]}"}
                    st = self._call("job_status", job_id=parts[1])
                    g["state"] = st.get("state")
                    g["metrics"] = st.get("metrics")
                    g["rescale"] = st.get("rescale")
                    return 200, g
                if parts == ["taskmanagers"]:
                    return 200, {"taskmanagers": self._call("list_runners")}
                if parts == ["traces"]:
                    from flink_tpu.obs.tracing import tracer

                    prefix = q.get("name", [""])[0]
                    return 200, {"spans": tracer.spans(prefix)}
                if parts == ["flamegraph"]:
                    from flink_tpu.obs.tracing import sample_threads

                    seconds = min(max(
                        float(q.get("seconds", ["1"])[0]), 0.05), 10.0)
                    hz = min(max(float(q.get("hz", ["50"])[0]), 1.0), 200.0)
                    return 200, sample_threads(seconds, hz)
                return 404, {"error": f"no route {u.path}"}
            if method == "PATCH" and len(parts) == 2 and parts[0] == "jobs":
                mode = q.get("mode", ["cancel"])[0]
                st = self._call("job_status", job_id=parts[1])
                if st.get("state") == "UNKNOWN":
                    return 404, {"error": f"no job {parts[1]}"}
                if mode == "cancel":
                    return 202, self._call("cancel_job", job_id=parts[1])
                if mode == "rescale":
                    # ref: the REST rescale endpoint (PATCH with a new
                    # parallelism) driving the AdaptiveScheduler
                    try:
                        devices = int(q.get("devices", [""])[0])
                    except ValueError:
                        return 400, {"error": "rescale needs devices=N"}
                    try:
                        processes = (int(q["processes"][0])
                                     if "processes" in q else None)
                    except ValueError:
                        return 400, {"error": "processes must be an int"}
                    resp = self._call("rescale_job", job_id=parts[1],
                                      devices=devices,
                                      processes=processes)
                    return (202 if resp.get("ok") else 409), resp
                return 400, {"error": f"unsupported mode {mode!r}"}
            if (method == "POST" and len(parts) == 3 and parts[0] == "jobs"
                    and parts[2] == "savepoints"):
                st = self._call("job_status", job_id=parts[1])
                if st.get("state") == "UNKNOWN":
                    return 404, {"error": f"no job {parts[1]}"}
                resp = self._call("trigger_savepoint", job_id=parts[1])
                return (202 if resp.get("ok") else 409), resp
            return 404, {"error": f"no route {method} {u.path}"}
        except Exception as e:  # noqa: BLE001 — HTTP boundary
            return 500, {"error": str(e)}

    def _index_html(self) -> str:
        """The web UI: one static page, no framework, no build step —
        JS fetches /jobs, /jobs/<id>/graph and /taskmanagers every 2s
        and renders the job DAG (stage chain with per-stage execution
        state), throughput/backpressure gauges, and checkpoint history
        (ref: the Flink web dashboard job graph + backpressure tab,
        rendered from the same REST the CLI uses)."""
        return _UI_HTML

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


_UI_HTML = """<!DOCTYPE html>
<html><head><title>flink_tpu</title><style>
body{font-family:system-ui,sans-serif;margin:24px;background:#fafafa}
h1{font-size:20px} h2{font-size:15px;margin:18px 0 6px}
table{border-collapse:collapse;font-size:13px}
td,th{border:1px solid #ccc;padding:3px 8px;text-align:left}
.dag{display:flex;align-items:center;flex-wrap:wrap;margin:6px 0}
.stage{border:1.5px solid #555;border-radius:6px;padding:6px 10px;
  margin:3px;background:#fff;font-size:12px;min-width:110px}
.stage .nm{font-weight:600}
.arrow{margin:0 4px;color:#888;font-size:16px}
.RUNNING{border-color:#2a7} .FAILED{border-color:#c33}
.FINISHED{border-color:#57c} .CANCELED{border-color:#999}
.gauge{display:inline-block;width:120px;height:10px;background:#eee;
  border-radius:5px;overflow:hidden;vertical-align:middle}
.gauge i{display:block;height:100%;background:#e80}
.kv{font-size:12px;color:#333;margin:2px 0}
</style></head><body>
<h1>flink_tpu cluster</h1>
<div id="jobs"></div>
<h2>Runners</h2><div id="runners"></div>
<p style="font-size:11px;color:#777">REST: /overview /jobs
/jobs/&lt;id&gt; /jobs/&lt;id&gt;/graph /taskmanagers — refreshes every 2s</p>
<script>
async function j(u){const r=await fetch(u);return r.json()}
function esc(x){return String(x).replace(/[&<>"']/g,
  c=>({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[c]))}
function fmtB(n){if(n<0||n==null)return"-";
  return n>1e6?(n/1e6).toFixed(1)+" MB":(n/1e3).toFixed(0)+" KB"}
async function tick(){
  const jobs=(await j("/jobs")).jobs||[];
  let html="";
  for(const jb of jobs){
    const g=await j("/jobs/"+encodeURIComponent(jb.job_id)+"/graph");
    const m=g.metrics||{};
    html+="<h2>job "+esc(jb.job_id)+" — "+esc(g.state||jb.state)+
      " (attempt "+jb.attempts+")</h2>";
    const stages=(g.vertices||[]).reduce((a,v)=>{
      (a[v.stage]=a[v.stage]||[]).push(v);return a},{});
    const names=g.stages||Object.keys(stages);
    html+='<div class="dag">';
    names.forEach((s,i)=>{
      const vs=stages[s]||[];
      const at=vs.length?(vs[0].attempts||vs[0].executions||[]):[];
      const st=at.length?at[at.length-1].state:"?";
      html+='<div class="stage '+esc(st)+'"><div class="nm">'+esc(s)+
        '</div><div>'+vs.length+"&times; "+esc(st)+'</div></div>';
      if(i<names.length-1)html+='<span class="arrow">&#8594;</span>';
    });
    html+="</div>";
    if(m&&m.eps!=null){
      const bp=Math.min(100,Math.round(m.backpressure_pct||0));
      html+='<div class="kv">throughput: <b>'+
        (m.eps>1e6?(m.eps/1e6).toFixed(2)+"M":Math.round(m.eps))+
        ' rec/s</b> &nbsp; records in/out: '+m.records_in+"/"+
        m.records_out+' &nbsp; watermark lag: '+
        Math.round(m.wm_lag_ms||0)+'ms</div>';
      const dp=Math.min(100,Math.round(m.drain_busy_pct||0));
      html+='<div class="kv">backpressure: <span class="gauge">'+
        '<i style="width:'+bp+'%"></i></span> '+bp+
        "% &nbsp; drain link: <span class=\"gauge\">"+
        '<i style="width:'+dp+'%"></i></span> '+dp+"%</div>";
      const rc=g.rescale||{};const rm=rc.metrics||{};
      if(rc.pending_devices!=null){
        html+='<div class="kv">rescale: <b>in flight</b> &#8594; '+
          rc.pending_devices+' dev &times; '+
          (rc.pending_processes||1)+' proc ('+
          (rc.savepoints_collected||0)+' savepoints in)</div>';
      }else if(rm["coordinator.rescale.armed"]){
        html+='<div class="kv">rescale: '+
          rm["coordinator.rescale.armed"]+' armed / '+
          (rm["coordinator.rescale.redeploy"]||0)+' completed / '+
          (rm["coordinator.rescale.disarmed"]||0)+
          ' disarmed &nbsp; time-to-rescale p50: '+
          Math.round(rm["coordinator.rescale.duration_ms.p50"]||0)+
          'ms</div>';
      }
      if(m.checkpoints&&m.checkpoints.length){
        html+="<table><tr><th>checkpoint</th><th>time</th>"+
          "<th>size</th></tr>"+m.checkpoints.map(c=>
          "<tr><td>chk-"+c.id+"</td><td>"+
          new Date(c.ts).toLocaleTimeString()+"</td><td>"+
          fmtB(c.bytes)+"</td></tr>").join("")+"</table>";
      }
    }
  }
  if(!jobs.length)html="<p>no jobs</p>";
  document.getElementById("jobs").innerHTML=html;
  const rs=(await j("/taskmanagers")).taskmanagers||{};
  document.getElementById("runners").innerHTML=
    "<table><tr><th>id</th><th>status</th><th>devices</th></tr>"+
    Object.entries(rs).map(([id,r])=>"<tr><td>"+esc(id)+"</td><td>"+
      (r.alive?"alive":"lost")+"</td><td>"+r.n_devices+
      "</td></tr>").join("")+"</table>";
}
tick();setInterval(tick,2000);
</script></body></html>"""
