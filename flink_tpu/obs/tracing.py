"""Tracing: spans for checkpoint/recovery + on-demand thread sampling.

ref: SURVEY §6.1 — flink-core ``traces/`` Span/TraceReporter (emitted
for checkpointing and job recovery from CheckpointStatsTracker), and
the REST-triggered flame graphs of runtime/webmonitor/threadinfo.
Latency markers (the third §6.1 mechanism) already ride the driver's
emit-latency histogram; this module adds the other two.

Design: a process-global ``Tracer`` with a bounded ring of completed
spans. Spans are cheap (one dataclass + two clock reads) and the ring
is lock-guarded but uncontended — span starts/ends happen on the
driver loop and checkpoint threads at human frequencies, never per
record. Reporters get each completed span synchronously (the
TraceReporter seam); the REST server exposes the ring at /traces and
aggregated thread stacks at /flamegraph.
"""
from __future__ import annotations

import collections
import dataclasses
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["Span", "Tracer", "tracer", "sample_threads"]


@dataclasses.dataclass
class Span:
    name: str
    start: float
    end: Optional[float] = None
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration_ms(self) -> Optional[float]:
        return None if self.end is None else (self.end - self.start) * 1e3

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "start": self.start,
                "duration_ms": self.duration_ms,
                "attributes": dict(self.attributes)}


class _SpanHandle:
    """Context manager recording one span; ``set(k, v)`` attaches
    attributes mid-flight (e.g. bytes persisted)."""

    def __init__(self, trc: "Tracer", span: Span) -> None:
        self._trc = trc
        self.span = span

    def set(self, key: str, value: Any) -> "_SpanHandle":
        self.span.attributes[key] = value
        return self

    def __enter__(self) -> "_SpanHandle":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.span.attributes["error"] = f"{type(exc).__name__}: {exc}"
        self._trc._finish(self.span)


class Tracer:
    def __init__(self, capacity: int = 512) -> None:
        self._done: collections.deque = collections.deque(maxlen=capacity)
        self._reporters: List[Callable[[Span], None]] = []
        self._lock = threading.Lock()

    def span(self, name: str, **attributes: Any) -> _SpanHandle:
        return _SpanHandle(self, Span(name, time.time(),
                                      attributes=dict(attributes)))

    def add_reporter(self, fn: Callable[[Span], None]) -> None:
        with self._lock:
            self._reporters.append(fn)

    def _finish(self, span: Span) -> None:
        span.end = time.time()
        with self._lock:
            self._done.append(span)
            reporters = list(self._reporters)
        for r in reporters:
            try:
                r(span)
            except Exception:  # noqa: BLE001 — reporters must not break jobs
                pass

    def spans(self, name_prefix: str = "") -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._done)
        return [s.to_dict() for s in items
                if s.name.startswith(name_prefix)]

    def clear(self) -> None:
        with self._lock:
            self._done.clear()


# process-global tracer (the metric-registry pattern: one per process,
# sub-systems attach by name)
tracer = Tracer()


def sample_threads(seconds: float = 1.0, hz: float = 50.0) -> Dict[str, Any]:
    """Aggregate stack samples across all live threads — the flame-graph
    data (ref: JobVertexFlameGraphHandler / ThreadInfoSample: REST-
    triggered sampling, aggregated frames). Returns {stack -> count}
    with stacks rendered innermost-last as ';'-joined frames, plus the
    sampling parameters (collapsed format: feed straight to any
    flamegraph renderer)."""
    interval = 1.0 / hz
    counts: Dict[str, int] = {}
    deadline = time.time() + seconds
    n = 0
    while time.time() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == threading.get_ident():
                continue  # the sampler itself is noise
            frames = []
            f = frame
            while f is not None:
                code = f.f_code
                frames.append(f"{code.co_name}@"
                              f"{code.co_filename.rsplit('/', 1)[-1]}:"
                              f"{f.f_lineno}")
                f = f.f_back
            stack = ";".join(reversed(frames))
            counts[stack] = counts.get(stack, 0) + 1
        n += 1
        time.sleep(interval)
    return {"samples": n, "seconds": seconds, "hz": hz, "stacks": counts}
