"""The window operator — the north-star component.

ref: streaming/runtime/operators/windowing/WindowOperator.java
(processElement: assign windows → per-(key,window) state add → trigger;
onEventTime: fire → emit via InternalWindowFunction → purge) and the
timer loop it rides (streaming/api/operators/InternalTimerServiceImpl
.advanceWatermark — a per-timer heap poll).

TPU-first redesign (SURVEY §6.7, §8): no per-element window lists, no
timer heap, no per-key callbacks. Three dense kernels over a
``(slots, pane_ring)`` accumulator tensor:

- ``apply``: one microbatch → pane index per record → masked scatter
  add/max/min into (slot, pane) cells. Sliding windows cost ONE write
  per element (the Table-runtime slicing trick, ref SliceAssigner), not
  ``size/slide`` writes like the reference's DataStream WindowOperator.
- ``fire``: a watermark advance makes whole *windows* fireable at once;
  each is a gather of its ``panes_per_window`` ring columns + a
  sum/max/min reduction over the pane axis — vectorized over every key
  slot simultaneously (the batched Trigger.onEventTime).
- ``clear``: panes no window can ever need again (watermark past
  end + allowed lateness) are reset to identities; the ring reuses them.

The host-side ``WindowOperator`` class owns the watermark clock, the ring
bookkeeping (which global pane lives in which ring column), allowed
lateness / late side output, and late re-firing — control flow the
reference keeps in triggers/timers, which is inherently scalar and cheap,
so it stays on the host while all per-record and per-key work is on
device.
"""
from __future__ import annotations

import dataclasses
import functools
from collections.abc import Mapping
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from flink_tpu.api.windowing import WindowAssigner
from flink_tpu.ops.aggregates import LaneAggregate
from flink_tpu.parallel.mesh import AXIS, MeshPlan
from flink_tpu.state.keyed import KeyDirectory, PaneState, PaneStateLayout, init_state
from flink_tpu.time.watermarks import LONG_MIN


# ---------------------------------------------------------------------------
# Pure kernels (jittable; operate on LOCAL slot ids).
# ---------------------------------------------------------------------------

def apply_kernel(
    state: PaneState,
    slot_ids: jax.Array,   # (B,) int32/int64 local slots; dump row for invalid
    ts: jax.Array,         # (B,) int64
    valid: jax.Array,      # (B,) bool
    data: Dict[str, jax.Array],
    *,
    agg: LaneAggregate,
    pane_ms: int,
    offset_ms: int,
    ring: int,
    dump_row: int,
) -> PaneState:
    """Fold one microbatch into pane state (the processElement hot loop,
    batched). All shapes static; invalid rows scatter into the dump row
    with identity lane values (doubly safe)."""
    pane = (ts - offset_ms) // pane_ms
    ring_ix = (pane % ring).astype(jnp.int32)
    rows = jnp.where(valid, slot_ids, dump_row).astype(jnp.int32)

    s_l, mx_l, mn_l = agg.lift_masked(data, valid)
    new = PaneState(
        sums=state.sums.at[rows, ring_ix].add(s_l),
        maxs=state.maxs.at[rows, ring_ix].max(mx_l),
        mins=state.mins.at[rows, ring_ix].min(mn_l),
        counts=state.counts.at[rows, ring_ix].add(valid.astype(jnp.int32)),
    )
    return new


def fire_kernel(
    state: PaneState,
    end_panes: jax.Array,  # (W,) int64 global pane ids (window end, exclusive)
    w_valid: jax.Array,    # (W,) bool
    pane_lo: jax.Array,    # scalar int64: oldest written-and-uncleared pane
    pane_hi: jax.Array,    # scalar int64: newest written pane
    *,
    panes_per_window: int,
    ring: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Evaluate every (key, fireable-window) pair at once.

    Returns (sums (rows,W,sw), maxs, mins, counts (rows,W)) — the lane
    reduction over each window's pane span. ref role: WindowOperator.
    onEventTime → emitWindowContents, for all keys in one shot.

    The [pane_lo, pane_hi] range masks ring aliasing: a window's pane that
    was never written (or already purged) may share a ring column with a
    newer pane; such cells read as identity. The ingest-side ring guard
    ensures at most one live pane per column within the range.
    """
    ppw = panes_per_window
    want = end_panes[:, None] - ppw + jnp.arange(ppw)[None, :]            # (W, ppw) global panes
    ring_ix = (want % ring).astype(jnp.int32)
    live = (want >= pane_lo) & (want <= pane_hi)                           # (W, ppw)
    m3 = live[None, :, :, None]
    m2 = live[None, :, :]
    sums = jnp.sum(jnp.where(m3, state.sums[:, ring_ix, :], 0.0), axis=2)   # (rows, W, sw)
    maxs = jnp.max(jnp.where(m3, state.maxs[:, ring_ix, :], -jnp.inf), axis=2)
    mins = jnp.min(jnp.where(m3, state.mins[:, ring_ix, :], jnp.inf), axis=2)
    counts = jnp.sum(jnp.where(m2, state.counts[:, ring_ix], 0), axis=2)    # (rows, W)
    counts = jnp.where(w_valid[None, :], counts, 0)
    return sums, maxs, mins, counts


def fire_pack_kernel(
    state: PaneState,
    end_panes: jax.Array,   # (W,) int64
    w_valid: jax.Array,     # (W,) bool
    pane_lo: jax.Array,
    pane_hi: jax.Array,
    used_mask: jax.Array,   # (rows,) bool — registered-key rows
    *,
    agg: LaneAggregate,
    panes_per_window: int,
    ring: int,
) -> jax.Array:
    """fire + select + finalize entirely on device, packed into ONE
    int32 buffer so the host pays exactly one transfer per firing
    advance. The device→host round trip is the latency floor of the
    emit path, and (crucially) separate result arrays do NOT pipeline
    when the ingest thread shares the transport — so everything rides
    one buffer: row 0 = [n, 0, ...]; rows 1..K = [slot_row, end_pane
    delta vs pane_lo, count, f32-bitcast result lanes...] with result
    columns in sorted-field order.

    ref role: the whole onEventTime → emitWindowContents →
    Collector.collect chain, batched."""
    sums, maxs, mins, counts = fire_kernel(
        state, end_panes, w_valid, pane_lo, pane_hi,
        panes_per_window=panes_per_window, ring=ring)
    rows = counts.shape[0]
    W = end_panes.shape[0]
    nz = (counts > 0) & used_mask[:, None] & w_valid[None, :]
    flat = nz.reshape(-1)
    k = rows * W
    idx = jnp.nonzero(flat, size=k, fill_value=k)[0]
    row = (idx // W).astype(jnp.int32)
    wi = (idx % W).astype(jnp.int32)
    row_c = jnp.minimum(row, rows - 1)
    sel_counts = counts[row_c, wi]
    res = agg.finalize(sums[row_c, wi], maxs[row_c, wi], mins[row_c, wi], sel_counts)
    end_delta = (end_panes[wi] - pane_lo).astype(jnp.int32)
    cols = [row, end_delta, sel_counts.astype(jnp.int32)]
    for name in sorted(res):
        v = res[name].reshape(k)
        if jnp.issubdtype(v.dtype, jnp.integer):
            # integer result lanes (counts) stay exact i32; float lanes
            # ride as f32 bitcasts (decode reads the dtype probe)
            cols.append(v.astype(jnp.int32))
        else:
            cols.append(lax.bitcast_convert_type(v.astype(jnp.float32), jnp.int32))
    body = jnp.stack(cols, axis=1)                       # (K, C)
    head = jnp.zeros((1, body.shape[1]), jnp.int32).at[0, 0].set(
        jnp.sum(flat).astype(jnp.int32))
    return jnp.concatenate([head, body])                 # (K+1, C)


def clear_kernel(state: PaneState, clear_mask: jax.Array) -> PaneState:
    """Reset ring columns selected by clear_mask (ring,) to identities
    (ref role: WindowOperator.clearAllState / registerCleanupTimer)."""
    m3 = clear_mask[None, :, None]
    m2 = clear_mask[None, :]
    return PaneState(
        sums=jnp.where(m3, 0.0, state.sums),
        maxs=jnp.where(m3, -jnp.inf, state.maxs),
        mins=jnp.where(m3, jnp.inf, state.mins),
        counts=jnp.where(m2, 0, state.counts),
    )


_JIT_APPLY = jax.jit(
    apply_kernel,
    static_argnames=("agg", "pane_ms", "offset_ms", "ring", "dump_row"))
_JIT_FIRE_PACK = jax.jit(
    fire_pack_kernel,
    static_argnames=("agg", "panes_per_window", "ring"))
_JIT_CLEAR = jax.jit(clear_kernel)

# catch-up fires are evaluated in chunks of this many windows so they
# reuse the steady-state compiled kernels (pow2 pads: 1,2) and keep each
# packed buffer small — device→host bandwidth is the emit ceiling and
# chunked buffers still fetch together in one round trip
MAX_FIRE_CHUNK = 2


# ---------------------------------------------------------------------------
# Planning: static layout from assigner + timing characteristics.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class WindowPlan:
    pane_ms: int
    offset_ms: int
    size_ms: int
    slide_ms: int
    panes_per_window: int
    panes_per_slide: int
    ring: int
    allowed_lateness_ms: int

    @classmethod
    def plan(
        cls,
        assigner: WindowAssigner,
        *,
        allowed_lateness_ms: int = 0,
        max_out_of_orderness_ms: int = 0,
        headroom_panes: int = 4,
    ) -> "WindowPlan":
        pane = assigner.pane_ms
        # Live pane span: a pane stays until wm >= pane_start + size +
        # lateness; the newest writable pane is at max_ts = wm + delay.
        # headroom covers event time running ahead of the watermark clock
        # between advances (one microbatch's worth of time progress).
        span_ms = assigner.size_ms + allowed_lateness_ms + max_out_of_orderness_ms
        ring = -(-span_ms // pane) + 1 + headroom_panes
        if ring > 65536:
            raise ValueError(
                f"pane ring of {ring} panes (pane={pane}ms from gcd(size={assigner.size_ms},"
                f" slide={assigner.slide_ms})) is degenerate — choose a slide that divides"
                " the window size (or shares a larger common divisor)")
        return cls(
            pane_ms=pane,
            offset_ms=assigner.offset_ms,
            size_ms=assigner.size_ms,
            slide_ms=assigner.slide_ms,
            panes_per_window=assigner.panes_per_window,
            panes_per_slide=assigner.panes_per_slide,
            ring=ring,
            allowed_lateness_ms=allowed_lateness_ms,
        )

    def pane_of(self, ts: np.ndarray) -> np.ndarray:
        return (ts - self.offset_ms) // self.pane_ms

    def window_end_ms(self, end_pane: int) -> int:
        return int(end_pane) * self.pane_ms + self.offset_ms

    def window_dead(self, end_pane: int, wm: int) -> bool:
        """A window is dead (late beyond lateness) iff
        window.maxTimestamp() + allowedLateness <= watermark
        (ref: WindowOperator.isWindowLate / isCleanupTime)."""
        end_ms = end_pane * self.pane_ms + self.offset_ms
        return end_ms - 1 + self.allowed_lateness_ms <= wm

    def first_dead_pane(self, wm: int) -> int:
        """Panes strictly below this are finally purged at watermark wm:
        the LAST window containing the pane is dead. Exact reference
        boundary: ((p//pps)*pps + ppw) is that window's end pane."""
        if wm == LONG_MIN:
            return np.iinfo(np.int64).min // 2
        pps, ppw = self.panes_per_slide, self.panes_per_window
        t = wm + 1 - self.allowed_lateness_ms - self.offset_ms
        q = t // self.pane_ms - ppw
        return (q // pps + 1) * pps

    def fireable_end_panes(
        self, wm_prev: int, wm_now: int, min_pane_seen: Optional[int] = None
    ) -> List[int]:
        """Slide-aligned window end panes e with wm_prev < end-1 <= wm_now
        — the first-time firings this advance unlocks (batched
        EventTimeTrigger: fire iff wm >= window.maxTimestamp).

        min_pane_seen bounds the range at job start (windows entirely
        before the first record are empty and never emit anyway).
        """
        if wm_now == LONG_MIN:
            return []
        pps, ppw = self.panes_per_slide, self.panes_per_window
        # Window STARTS are slide-aligned (multiples of pps), so END panes
        # satisfy e ≡ ppw (mod pps) — not e ≡ 0 unless size % slide == 0.
        def align_down(m: int) -> int:
            return m - ((m - ppw) % pps)

        # window end time must satisfy end - 1 <= wm  => end_ms <= wm + 1
        hi_end = align_down((wm_now + 1 - self.offset_ms) // self.pane_ms)
        if wm_prev == LONG_MIN:
            if min_pane_seen is None:
                return []
            lo_end = align_down(min_pane_seen)
        else:
            lo_end = align_down((wm_prev + 1 - self.offset_ms) // self.pane_ms)
        out = []
        e = lo_end + pps
        while e <= hi_end:
            out.append(int(e))
            e += pps
        return out


# ---------------------------------------------------------------------------
# Host-side operator runtime (single shard range; the sharded pipeline in
# exchange/ reuses the same kernels inside shard_map).
# ---------------------------------------------------------------------------

class WindowOperator:
    """Drives the kernels for one keyed window aggregation.

    Semantics golden-checked against the reference's WindowOperatorTest
    behaviours (ref: flink-streaming-java/src/test/.../windowing/
    WindowOperatorTest.java): event-time firing, allowed lateness with
    late re-firings, late-beyond-lateness side output, purge on cleanup.
    """

    def __init__(
        self,
        assigner: WindowAssigner,
        agg: LaneAggregate,
        *,
        num_shards: int = 128,
        slots_per_shard: int = 1024,
        allowed_lateness_ms: int = 0,
        max_out_of_orderness_ms: int = 0,
        shard_range: Optional[Tuple[int, int]] = None,
        mesh_plan: Optional[MeshPlan] = None,
        exchange_capacity: Optional[int] = None,
    ) -> None:
        self.assigner = assigner
        self.agg = agg
        self.mesh_plan = mesh_plan
        self.exchange_capacity = exchange_capacity
        self.plan = WindowPlan.plan(
            assigner,
            allowed_lateness_ms=allowed_lateness_ms,
            max_out_of_orderness_ms=max_out_of_orderness_ms,
        )
        if mesh_plan is not None:
            num_shards = mesh_plan.num_shards
            slots_per_shard = mesh_plan.slots_per_shard
            shard_range = None  # directory is global; devices own row blocks
        self.directory = KeyDirectory(num_shards, slots_per_shard, shard_range)
        per_block_slots = (
            mesh_plan.slots_per_device if mesh_plan else self.directory.local_slots)
        self.layout = PaneStateLayout(
            slots=per_block_slots,
            ring=self.plan.ring,
            sum_width=agg.sum_width,
            max_width=agg.max_width,
            min_width=agg.min_width,
        )
        self.watermark = LONG_MIN
        self._cleared_below = self.plan.first_dead_pane(LONG_MIN)  # panes < this are dead
        self._fired_below_end: Optional[int] = None  # highest end pane fired
        self._refire: set[int] = set()
        self._min_pane_seen: Optional[int] = None
        self._max_pane_seen: Optional[int] = None
        self.late_records: int = 0
        self.exchange_overflow: int = 0

        if mesh_plan is None:
            self.state = init_state(self.layout)
            self._build_local_kernels()
        else:
            self.state = self._init_sharded_state()
            self._build_sharded_kernels()

    # -- kernel construction --------------------------------------------
    def _build_local_kernels(self) -> None:
        # module-level jits (statics in the cache key) so operators with
        # equal configuration — across jobs in one process — share one
        # compiled kernel instead of recompiling per instance
        self._apply = functools.partial(
            _JIT_APPLY,
            agg=self.agg,
            pane_ms=self.plan.pane_ms,
            offset_ms=self.plan.offset_ms,
            ring=self.plan.ring,
            dump_row=self.layout.slots,
        )
        self._fire_pack = functools.partial(
            _JIT_FIRE_PACK,
            agg=self.agg,
            panes_per_window=self.plan.panes_per_window,
            ring=self.plan.ring,
        )
        self._clear = _JIT_CLEAR

    def _init_sharded_state(self) -> PaneState:
        mp = self.mesh_plan
        total_rows = mp.n_devices * self.layout.rows
        sharding = mp.row_sharding()

        @functools.partial(jax.jit, out_shardings=sharding)
        def init():
            return PaneState(
                sums=jnp.zeros((total_rows, self.layout.ring, self.layout.sum_width), jnp.float32),
                maxs=jnp.full((total_rows, self.layout.ring, self.layout.max_width), -jnp.inf, jnp.float32),
                mins=jnp.full((total_rows, self.layout.ring, self.layout.min_width), jnp.inf, jnp.float32),
                counts=jnp.zeros((total_rows, self.layout.ring), jnp.int32),
            )

        return init()

    def _build_sharded_kernels(self) -> None:
        """The full distributed hot path: per-device bucket-by-owner →
        all_to_all over the mesh (keyBy repartition on ICI) → local pane
        scatter. Fire/clear are embarrassingly parallel over row blocks.
        """
        from flink_tpu.exchange.keyby import keyby_exchange

        mp = self.mesh_plan
        agg = self.agg
        plan = self.plan
        layout = self.layout
        spd = mp.slots_per_device
        n_dev = mp.n_devices

        def apply_shard(state, slot, ts, valid, data):
            cap = self.exchange_capacity or slot.shape[0]
            dest = jnp.where(valid, slot // spd, 0).astype(jnp.int32)
            payload = {"__slot__": slot, "__ts__": ts, **data}
            recv, rvalid, overflow = keyby_exchange(
                dest, valid, payload, n_devices=n_dev, capacity=cap)
            my = lax.axis_index(AXIS)
            local_slot = recv["__slot__"] - my.astype(jnp.int64) * spd
            new_state = apply_kernel(
                state, local_slot, recv["__ts__"], rvalid,
                {k: v for k, v in recv.items() if not k.startswith("__")},
                agg=agg, pane_ms=plan.pane_ms, offset_ms=plan.offset_ms,
                ring=plan.ring, dump_row=layout.slots)
            return new_state, lax.psum(jnp.sum(overflow), AXIS)

        rows_local = layout.rows

        def fire_shard(state, end_panes, w_valid, lo, hi, used_mask):
            packed = fire_pack_kernel(
                state, end_panes, w_valid, lo, hi, used_mask,
                agg=agg, panes_per_window=plan.panes_per_window, ring=plan.ring)
            # globalize row ids (each device block carries its own rows);
            # column 0 of body rows is the slot row, head row 0 holds n
            my = lax.axis_index(AXIS).astype(jnp.int32)
            offset = jnp.zeros_like(packed[:, 0]).at[1:].set(my * rows_local)
            return packed.at[:, 0].add(offset)

        state_spec = jax.tree_util.tree_map(lambda _: P(AXIS), self.state)
        batch_spec = P(AXIS)
        rep = P()

        self._apply_sharded = jax.jit(
            jax.shard_map(
                apply_shard, mesh=mp.mesh,
                in_specs=(state_spec, batch_spec, batch_spec, batch_spec, batch_spec),
                out_specs=(state_spec, rep),
            )
        )
        self._fire_pack = jax.jit(
            jax.shard_map(
                fire_shard, mesh=mp.mesh,
                in_specs=(state_spec, rep, rep, rep, rep, P(AXIS)),
                out_specs=P(AXIS),
            )
        )
        self._clear = jax.jit(
            jax.shard_map(
                clear_kernel, mesh=mp.mesh,
                in_specs=(state_spec, rep),
                out_specs=state_spec,
            )
        )

    # -- data path -------------------------------------------------------
    def process_batch(
        self,
        keys: np.ndarray,
        ts: np.ndarray,
        data: Dict[str, np.ndarray],
        valid: Optional[np.ndarray] = None,
    ) -> None:
        """Fold a batch of records in. Late-beyond-lateness rows are
        dropped (side output; ref: WindowOperator sideOutput/
        numLateRecordsDropped) and late-within-lateness rows mark their
        windows for re-firing."""
        keys = np.asarray(keys, dtype=np.int64)
        ts = np.asarray(ts, dtype=np.int64)
        valid = np.ones(len(ts), bool) if valid is None else np.asarray(valid, bool)
        panes = self.plan.pane_of(ts)

        dead = self._cleared_below
        late_mask = valid & (panes < dead)
        self.late_records += int(late_mask.sum())
        valid = valid & ~late_mask

        if valid.any():
            mn = int(panes[valid].min())
            mx = int(panes[valid].max())
            if self._min_pane_seen is None or mn < self._min_pane_seen:
                self._min_pane_seen = mn
            if self._max_pane_seen is None or mx > self._max_pane_seen:
                self._max_pane_seen = mx

            # ring overflow guard: watermark clock must keep up with event
            # time (at most one live pane per ring column)
            live_lo = max(dead, self._min_pane_seen)
            if mx - live_lo >= self.plan.ring:
                raise RuntimeError(
                    f"pane ring overflow: pane {mx} vs oldest live {live_lo}, "
                    f"ring {self.plan.ring}; watermark lagging event time beyond "
                    "plan bounds (raise max_out_of_orderness_ms)")

        # late-but-allowed → re-fire affected, already-fired windows with
        # updated contents (ref: EventTimeTrigger.onElement fires
        # immediately for late elements within allowed lateness)
        if self._fired_below_end is not None:
            late_ok = valid & (panes < self._fired_below_end)
            if late_ok.any():
                pps = self.plan.panes_per_slide
                ppw = self.plan.panes_per_window
                for p in np.unique(panes[late_ok]).tolist():
                    # windows containing pane p start at pps-multiples in
                    # (p-ppw, p], so ends are (p//pps)*pps + ppw stepping
                    # down by pps while > p; skip windows already beyond
                    # allowed lateness (ref: isWindowLate skips the window,
                    # element still feeds its remaining live windows)
                    e = (p // pps) * pps + ppw
                    while e > p:
                        if e <= self._fired_below_end and not self.plan.window_dead(e, self.watermark):
                            self._refire.add(int(e))
                        e -= pps

        slots = self.directory.assign(keys)
        bad = slots < 0
        if bad.any():
            # shard full or misrouted: drop with accounting (spill backend
            # is the round-2 home for these)
            valid = valid & ~bad
        from flink_tpu.records import device_cast
        data = {k: device_cast(v) for k, v in data.items()}
        if self.mesh_plan is None:
            self.state = self._apply(
                self.state, jnp.asarray(slots), jnp.asarray(ts), jnp.asarray(valid),
                {k: jnp.asarray(v) for k, v in data.items()})
        else:
            # pad batch to a multiple of the device count (arrival split)
            n_dev = self.mesh_plan.n_devices
            b = len(ts)
            pad = (-b) % n_dev
            if pad:
                slots = np.concatenate([slots, np.zeros(pad, np.int64)])
                ts = np.concatenate([ts, np.zeros(pad, np.int64)])
                valid = np.concatenate([valid, np.zeros(pad, bool)])
                data = {k: np.concatenate([v, np.zeros((pad,) + v.shape[1:], v.dtype)])
                        for k, v in data.items()}
            self.state, overflow = self._apply_sharded(
                self.state, jnp.asarray(slots), jnp.asarray(ts), jnp.asarray(valid),
                {k: jnp.asarray(v) for k, v in data.items()})
            self.exchange_overflow += int(overflow)

    # -- time path -------------------------------------------------------
    def advance_watermark(self, wm: int) -> "FiredWindows":
        """Advance event time; fire newly-complete windows plus pending
        re-fires; purge dead panes. Returns the fired-window batch
        (key, window_start, window_end, count, result fields...) as a
        lazy ``FiredWindows`` — the device work is dispatched here, the
        single device→host transfer happens on first access."""
        if wm < self.watermark or (wm == self.watermark and not self._refire):
            return self._empty()
        prev = self.watermark
        self.watermark = wm

        if self._max_pane_seen is None:
            ends: List[int] = []
        else:
            # clamp the fire scan to windows that can contain data — a
            # large watermark jump (idle gap, end-of-input flush) must
            # not enumerate millions of provably-empty windows
            ends_wm = min(wm, self._last_data_end_ms() - 1)
            if prev != LONG_MIN and prev >= ends_wm:
                ends = []
            else:
                ends = self.plan.fireable_end_panes(prev, ends_wm, self._min_pane_seen)
        ends = sorted(set(ends) | self._refire)
        # the fired frontier must track the WATERMARK, not just enumerated
        # ends: a late-within-lateness record landing in any window the
        # watermark already passed (fired or empty-skipped) must trigger
        # an immediate late firing (ref: EventTimeTrigger.onElement FIREs
        # when window.maxTimestamp() <= currentWatermark)
        pps = self.plan.panes_per_slide
        ppw = self.plan.panes_per_window
        m = (wm + 1 - self.plan.offset_ms) // self.plan.pane_ms
        frontier = m - ((m - ppw) % pps)
        if self._fired_below_end is None or frontier > self._fired_below_end:
            self._fired_below_end = frontier
        self._refire.clear()
        out = self._fire_ends(ends)

        # purge panes no window can need anymore; only columns actually
        # written (>= min pane seen) can hold data
        new_dead = self.plan.first_dead_pane(wm)
        if new_dead > self._cleared_below:
            lo = self._cleared_below
            if self._min_pane_seen is not None:
                lo = max(lo, self._min_pane_seen)
            else:
                lo = new_dead  # nothing written yet — nothing to clear
            hi = new_dead
            if hi > lo:
                if hi - lo >= self.plan.ring:
                    mask = np.ones(self.plan.ring, dtype=bool)
                else:
                    ring_positions = np.arange(lo, hi) % self.plan.ring
                    mask = np.zeros(self.plan.ring, dtype=bool)
                    mask[ring_positions] = True
                self.state = self._clear(self.state, jnp.asarray(mask))
            self._cleared_below = new_dead
        return out

    def _fire_ends(self, ends: List[int]) -> "FiredWindows":
        if not ends or self._max_pane_seen is None:
            return self._empty()
        # windows entirely outside the written pane range are empty — skip
        lo = max(self._cleared_below, self._min_pane_seen)
        hi = self._max_pane_seen
        ppw = self.plan.panes_per_window
        ends = [e for e in ends if e > lo and e - ppw <= hi]
        if not ends:
            return self._empty()
        # pad the window axis to a power of two (compile once per bucket
        # size, not per distinct fire count) and CHUNK large fires at
        # MAX_FIRE_CHUNK windows: a catch-up advance reuses the small
        # steady-state kernels instead of compiling a one-off giant one
        used = self._used_mask_device()
        packs = []
        for c0 in range(0, len(ends), MAX_FIRE_CHUNK):
            chunk = ends[c0:c0 + MAX_FIRE_CHUNK]
            W = len(chunk)
            Wp = 1
            while Wp < W:
                Wp *= 2
            ends_padded = chunk + [chunk[-1]] * (Wp - W)
            end_arr = jnp.asarray(np.asarray(ends_padded, dtype=np.int64))
            w_valid = jnp.asarray(np.arange(Wp) < W)
            buf = self._fire_pack(
                self.state, end_arr, w_valid, jnp.int64(lo), jnp.int64(hi),
                used)
            # start the device→host copy NOW (non-blocking): by the time
            # the drain thread materializes, the data is already local
            buf.copy_to_host_async()
            packs.append((lo, buf))
        return FiredWindows(op=self, packs=packs)

    def _result_fields(self) -> List[str]:
        """Sorted result-lane field names — the packed buffer's column
        order past [row, end_delta, count]. MUST mirror
        fire_pack_kernel's ``sorted(res)`` exactly (including a result
        field named 'count' if the aggregate emits one)."""
        if not hasattr(self, "_res_fields"):
            agg = self.agg
            res = agg.finalize(
                np.zeros((0, agg.sum_width), np.float32),
                np.zeros((0, agg.max_width), np.float32),
                np.zeros((0, agg.min_width), np.float32),
                np.zeros((0,), np.int32))
            self._res_fields = sorted(res)
            self._res_is_int = {
                k: np.issubdtype(np.asarray(res[k]).dtype, np.integer)
                for k in res
            }
        return self._res_fields

    def _decode_packs(self, packs, bufs) -> Dict[str, np.ndarray]:
        """Host-side decode of fetched fire buffers (bitcast lanes,
        slot → key, pane → window times)."""
        fields = self._result_fields()
        segs = []  # (buffer_body_slice, lo)
        for (lo, _), buf in zip(packs, bufs):
            if self.mesh_plan is None:
                n = int(buf[0, 0])
                segs.append((buf[1:1 + n], lo))
            else:
                blk = len(buf) // self.mesh_plan.n_devices
                for d in range(self.mesh_plan.n_devices):
                    block = buf[d * blk:(d + 1) * blk]
                    n = int(block[0, 0])
                    segs.append((block[1:1 + n], lo))
        if segs:
            body = np.concatenate([s for s, _ in segs])
            lo_col = np.concatenate(
                [np.full(len(s), lo, np.int64) for s, lo in segs])
        else:
            body = np.zeros((0, 3 + len(fields)), np.int32)
            lo_col = np.zeros(0, np.int64)
        rows = body[:, 0]
        end_pane = lo_col + body[:, 1]
        window_end = end_pane * self.plan.pane_ms + self.plan.offset_ms
        out: Dict[str, np.ndarray] = {
            "key": self.directory.key_of_slots(self._slot_of_rows(rows)),
            "window_start": window_end - self.plan.size_ms,
            "window_end": window_end,
            "count": body[:, 2],
        }
        for i, k in enumerate(fields):
            if k == "count":
                continue  # the exact i32 column beats the bitcast lane
            col = np.ascontiguousarray(body[:, 3 + i])
            out[k] = col if self._res_is_int[k] else col.view(np.float32)
        return out

    def _used_mask_device(self) -> jax.Array:
        """(rows,) bool on device, marking registered-key rows; re-pushed
        only when the directory registered new keys (h2d is cheap and
        one-way; the d2h round trip is what the packed fire avoids)."""
        nk = self.directory.num_keys()
        if getattr(self, "_used_pushed", -1) != nk:
            n_rows = self.layout.rows * (
                self.mesh_plan.n_devices if self.mesh_plan else 1)
            used = np.zeros(n_rows, dtype=bool)
            used_slots = np.nonzero(self.directory.used_mask())[0]
            used[self._row_of_slots(used_slots)] = True
            if self.mesh_plan is not None:
                self._used_dev = jax.device_put(used, self.mesh_plan.row_sharding())
            else:
                self._used_dev = jnp.asarray(used)
            self._used_pushed = nk
        return self._used_dev

    def _row_of_slots(self, slots: np.ndarray) -> np.ndarray:
        """Global slot id → row in the state array (sharded state carries
        one dump row per device block)."""
        if self.mesh_plan is None:
            return slots
        return self.mesh_plan.global_slot_to_row(slots)

    def _slot_of_rows(self, rows: np.ndarray) -> np.ndarray:
        if self.mesh_plan is None:
            return rows
        return rows - rows // self.layout.rows

    def _last_data_end_ms(self) -> int:
        """End time (ms) of the last window that can contain data (the
        final window covering ``_max_pane_seen``)."""
        pps = self.plan.panes_per_slide
        last_end = (self._max_pane_seen // pps) * pps + self.plan.panes_per_window
        return last_end * self.plan.pane_ms + self.plan.offset_ms

    def final_watermark(self) -> int:
        """Watermark that completes (and purges) every window that can
        hold data — the end-of-input flush point (ref role: advancing to
        Watermark.MAX_WATERMARK on input end, kept finite here)."""
        if self._max_pane_seen is None:
            return self.watermark if self.watermark != LONG_MIN else 0
        return self._last_data_end_ms() + self.plan.allowed_lateness_ms + 1

    def _empty(self) -> "FiredWindows":
        """Cached empty fired-batch (a fresh one would dispatch tiny
        device ops on every no-op watermark advance)."""
        if not hasattr(self, "_empty_cache"):
            self._empty_cache = _empty_fired(self.agg)
        return FiredWindows(data=dict(self._empty_cache))

    # -- snapshot seam (checkpoint/ uses this) ---------------------------
    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "n_dev": self.mesh_plan.n_devices if self.mesh_plan else 1,
            "panes": jax.tree_util.tree_map(np.asarray, self.state),
            "directory": self.directory.snapshot(),
            "watermark": self.watermark,
            "cleared_below": self._cleared_below,
            "fired_below_end": self._fired_below_end,
            "min_pane_seen": self._min_pane_seen,
            "max_pane_seen": self._max_pane_seen,
            "refire": sorted(self._refire),
            "late_records": self.late_records,
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        panes = snap["panes"]
        snap_dev = snap.get("n_dev", 1)
        cur_dev = self.mesh_plan.n_devices if self.mesh_plan else 1
        if snap_dev != cur_dev:
            # RESHARD: the key-shard space is fixed (the maxParallelism
            # contract) but the device count changed — re-block the row
            # axis, dropping the old per-block dump rows and inserting
            # fresh ones (ref role: StateAssignmentOperation re-splitting
            # key-group ranges on rescale)
            panes = _reblock_panes(panes, snap_dev, cur_dev)
        state = jax.tree_util.tree_map(jnp.asarray, panes)
        if self.mesh_plan is not None:
            state = jax.device_put(state, self.mesh_plan.row_sharding())
        self.state = state
        self.directory = KeyDirectory.restore(
            self.directory.num_shards, self.directory.slots_per_shard,
            snap["directory"], (self.directory.shard_lo, self.directory.shard_hi))
        self.watermark = snap["watermark"]
        self._cleared_below = snap["cleared_below"]
        self._fired_below_end = snap["fired_below_end"]
        self._min_pane_seen = snap["min_pane_seen"]
        self._max_pane_seen = snap["max_pane_seen"]
        self._refire = set(snap["refire"])
        self.late_records = snap["late_records"]
        self._used_pushed = -1  # directory changed: invalidate device used-mask


def _reblock_panes(panes: PaneState, old_dev: int, new_dev: int) -> PaneState:
    """Re-block state rows from old_dev device blocks to new_dev blocks.
    Each block is (slots_local + 1 dump) rows; logical slot order is
    preserved (global slot = shard * slots_per_shard, contiguous)."""

    def reblock(arr: np.ndarray, dump_fill) -> np.ndarray:
        arr = np.asarray(arr)
        rpl = arr.shape[0] // old_dev          # rows per old block
        blocks = [arr[d * rpl:(d + 1) * rpl - 1] for d in range(old_dev)]
        logical = np.concatenate(blocks)       # (total_slots, ...)
        if logical.shape[0] % new_dev != 0:
            raise ValueError(
                f"cannot reshard {logical.shape[0]} slots onto {new_dev} "
                "devices — num_shards * slots_per_shard must be divisible "
                "by the device count (the key-group contract)")
        slots_new = logical.shape[0] // new_dev
        out = []
        for d in range(new_dev):
            blk = logical[d * slots_new:(d + 1) * slots_new]
            dump = np.full((1,) + arr.shape[1:], dump_fill, dtype=arr.dtype)
            out.append(np.concatenate([blk, dump]))
        return np.concatenate(out)

    return PaneState(
        sums=reblock(panes.sums, 0.0),
        maxs=reblock(panes.maxs, -np.inf),
        mins=reblock(panes.mins, np.inf),
        counts=reblock(panes.counts, 0),
    )


class FiredWindows(Mapping):
    """A fired-window batch with lazy host materialization.

    The device work (fire + select + finalize) was already dispatched
    when this object was created; only the device→host transfer is
    deferred to first access. The runtime driver drains these on a
    separate thread — the analogue of the reference handing serialized
    buffers to Netty's IO thread off the mailbox thread (ref:
    runtime/io/network/api/writer/RecordWriter.java → PipelinedSubpartition
    .notifyDataAvailable), so emission latency never blocks ingest.
    ``materialize_many`` fetches a whole backlog of fires in ONE
    device→host round trip (the transport serializes round trips, so
    one per fire is the emit-path latency floor — batch them)."""

    def __init__(self, data: Optional[Dict[str, np.ndarray]] = None,
                 fetch=None, op=None, packs=None):
        self._data = data
        self._fetch = fetch
        self._op = op
        self._packs = packs

    def materialize(self) -> Dict[str, np.ndarray]:
        if self._data is None:
            if self._fetch is not None:
                self._data = self._fetch()
                self._fetch = None
            else:
                bufs = jax.device_get([b for _, b in self._packs])
                self._data = self._op._decode_packs(self._packs, bufs)
                self._packs = self._op = None
        return self._data

    @staticmethod
    def materialize_many(fireds: List["FiredWindows"]) -> None:
        """Fetch every pending buffer across ``fireds`` in one
        device_get, then decode each."""
        pending = [f for f in fireds if f._data is None and f._packs is not None]
        if not pending:
            return
        all_bufs = jax.device_get(
            [[b for _, b in f._packs] for f in pending])
        for f, bufs in zip(pending, all_bufs):
            f._data = f._op._decode_packs(f._packs, bufs)
            f._packs = f._op = None

    def __getitem__(self, key: str) -> np.ndarray:
        return self.materialize()[key]

    def __iter__(self):
        return iter(self.materialize())

    def __len__(self) -> int:
        return len(self.materialize())


def _empty_fired(agg: LaneAggregate) -> Dict[str, np.ndarray]:
    out = {
        "key": np.zeros(0, np.int64),
        "window_start": np.zeros(0, np.int64),
        "window_end": np.zeros(0, np.int64),
        "count": np.zeros(0, np.int32),
    }
    res = agg.finalize(
        jnp.zeros((0, agg.sum_width)), jnp.zeros((0, agg.max_width)),
        jnp.zeros((0, agg.min_width)), jnp.zeros((0,), jnp.int32))
    for k, v in res.items():
        out[k] = np.asarray(v)
    return out
