"""The window operator — the north-star component.

ref: streaming/runtime/operators/windowing/WindowOperator.java
(processElement: assign windows → per-(key,window) state add → trigger;
onEventTime: fire → emit via InternalWindowFunction → purge) and the
timer loop it rides (streaming/api/operators/InternalTimerServiceImpl
.advanceWatermark — a per-timer heap poll).

TPU-first redesign (SURVEY §6.7, §8): no per-element window lists, no
timer heap, no per-key callbacks. Three dense kernels over a
``(slots, pane_ring)`` accumulator tensor:

- ``apply``: one microbatch → pane index per record → masked scatter
  add/max/min into (slot, pane) cells. Sliding windows cost ONE write
  per element (the Table-runtime slicing trick, ref SliceAssigner), not
  ``size/slide`` writes like the reference's DataStream WindowOperator.
- ``fire``: a watermark advance makes whole *windows* fireable at once;
  each is a gather of its ``panes_per_window`` ring columns + a
  sum/max/min reduction over the pane axis — vectorized over every key
  slot simultaneously (the batched Trigger.onEventTime).
- ``clear``: panes no window can ever need again (watermark past
  end + allowed lateness) are reset to identities; the ring reuses them.

The host-side ``WindowOperator`` class owns the watermark clock, the ring
bookkeeping (which global pane lives in which ring column), allowed
lateness / late side output, and late re-firing — control flow the
reference keeps in triggers/timers, which is inherently scalar and cheap,
so it stays on the host while all per-record and per-key work is on
device.
"""
from __future__ import annotations

import collections
import dataclasses
import functools
import threading
import time
from collections.abc import Mapping
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from flink_tpu.api.windowing import WindowAssigner
from flink_tpu.hostsync import ready_wait
from flink_tpu.utils.jaxcompat import shard_map
from flink_tpu.ops.aggregates import LaneAggregate
from flink_tpu.parallel.mesh import AXIS, MeshPlan
from flink_tpu.state.keyed import (
    KeyDirectory, PaneState, PaneStateLayout, account_full_drop, init_state)
from flink_tpu.state.spill import HostSpillStore
from flink_tpu.time.watermarks import LONG_MIN


# ---------------------------------------------------------------------------
# Pure kernels (jittable; operate on LOCAL slot ids).
# ---------------------------------------------------------------------------

def apply_kernel(
    state: PaneState,
    packed: jax.Array,     # (B,) int: slot * ring + ring_ix; < 0 = invalid
    data: Dict[str, jax.Array],
    *,
    agg: LaneAggregate,
    ring: int,
    dump_row: int,
) -> PaneState:
    """Fold one microbatch into pane state (the processElement hot loop,
    batched). The host pre-packs each record's (slot, pane-ring column)
    into ONE integer — the only per-record value the scatter needs — so
    ingest ships a single narrow array instead of (slots, timestamps,
    validity) three-wide: host→device bytes are the transport currency
    on remote-attached chips. Negative = invalid → scatters into the
    dump row with identity lane values (doubly safe)."""
    valid = packed >= 0
    p = jnp.where(valid, packed, 0)
    rows = jnp.where(valid, p // ring, dump_row).astype(jnp.int32)
    ring_ix = (p % ring).astype(jnp.int32)
    return _scatter_panes(state, rows, ring_ix, valid, data, agg)


def _scatter_panes(state, rows, ring_ix, valid, data, agg):
    s_l, mx_l, mn_l = agg.lift_masked(data, valid)
    return PaneState(
        sums=(state.sums.at[rows, ring_ix].add(s_l)
              if state.sums is not None else None),
        maxs=(state.maxs.at[rows, ring_ix].max(mx_l)
              if state.maxs is not None else None),
        mins=(state.mins.at[rows, ring_ix].min(mn_l)
              if state.mins is not None else None),
        counts=state.counts.at[rows, ring_ix].add(valid.astype(jnp.int32)),
    )


INVALID_SLOT_U16 = 0xFFFF  # sentinel slot for invalid rows in split uploads


def split_decode(sc: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(B,3) uint8 → ((B,) uint16 slot, (B,) uint8 ring column). Bytes
    0-1 are the little-endian slot id (bitcast, matching numpy
    ``.view(uint8)`` on the host), byte 2 the ring column. Record-major
    layout so a shard_map partition along axis 0 keeps records whole."""
    slot = lax.bitcast_convert_type(sc[:, :2], jnp.uint16)
    return slot, sc[:, 2]


def split_encode(slots: np.ndarray, cols: np.ndarray,
                 valid: np.ndarray) -> np.ndarray:
    """Host half of ``split_decode``: (B,) slots + (B,) ring columns →
    (B,3) uint8 with 0xFFFF marking invalid rows."""
    n = len(slots)
    sl = np.where(valid, slots, INVALID_SLOT_U16).astype(np.uint16)
    sc = np.empty((n, 3), np.uint8)
    sc[:, :2] = sl.view(np.uint8).reshape(n, 2)
    sc[:, 2] = cols
    return sc


def apply_kernel_split(
    state: PaneState,
    sc: jax.Array,         # (B, 3) uint8: see split_decode
    data: Dict[str, jax.Array],
    *,
    agg: LaneAggregate,
    dump_row: int,
) -> PaneState:
    """``apply_kernel`` with the (slot, ring column) pair shipped as one
    (B,3) uint8 buffer instead of a packed int32 — 3 bytes/record on the
    host→device link instead of 4, in ONE transfer (a second buffer
    costs a second round trip on the tunnel-attached chip; measured
    708→515 ms/batch at 2^20). The link, not the MXU, is the Q5
    throughput ceiling (PROFILE.md §4), so bytes-and-trips is the
    currency; the kernel body is identical — the rows/ring_ix it needs
    decode in two device ops."""
    slot, col = split_decode(sc)
    valid = slot != INVALID_SLOT_U16
    rows = jnp.where(valid, slot.astype(jnp.int32), dump_row)
    ring_ix = col.astype(jnp.int32)
    return _scatter_panes(state, rows, ring_ix, valid, data, agg)


def apply_preagg_u16_kernel(
    state: PaneState,
    buf: jax.Array,        # (P, 3) uint16: [pair lo16, pair hi16, count]
    *,
    ring: int,
    dump_row: int,
) -> PaneState:
    """Fold a HOST-PRE-AGGREGATED microbatch in: the host combined the
    batch per (slot, ring column) pair with np.bincount (the mini-batch
    local-aggregation trick, ref: table/runtime mini-batch agg), so the
    upload carries one (pair id, count) triple per DISTINCT pair —
    ~6 bytes × (keys × panes touched) instead of 3 bytes × records.
    For Q5's 2^20-record batches over 10k keys that is ~0.6 B/record on
    a link that is the pipeline ceiling, and the device scatter shrinks
    by the same records/pairs ratio. Count-only shape (sum lanes ride
    the i32 variant). Sentinel pair 0xFFFFFFFF marks padding."""
    b = buf.astype(jnp.int32)
    pair = b[:, 0] | (b[:, 1] << 16)   # sentinel decodes to -1
    ok = pair >= 0
    p = jnp.where(ok, pair, 0)
    rows = jnp.where(ok, p // ring, dump_row).astype(jnp.int32)
    cols = (p % ring).astype(jnp.int32)
    cnt = jnp.where(ok, b[:, 2], 0)
    return PaneState(sums=state.sums, maxs=state.maxs, mins=state.mins,
                     counts=state.counts.at[rows, cols].add(cnt))


def apply_preagg_u32_kernel(
    state: PaneState,
    buf: jax.Array,        # (P,) uint32: pair << 12 | count (count < 0xFFF)
    *,
    ring: int,
    dump_row: int,
) -> PaneState:
    """Tightest count-only pre-agg upload: ONE u32 per distinct pair —
    20-bit pair id + 12-bit count. Eligible when the pair domain fits
    2^20 and every per-pair count < 0xFFF (the host checks both and
    falls back to the u16 triple otherwise). 4 bytes/pair: on a
    single-core host whose relay serializes transfers, upload bytes are
    CPU, so every byte shaved is host budget returned to the pipeline.
    Padding entries are 0xFFFFFFFF (pair 0xFFFFF, beyond the strict
    domain < 2^20 the eligibility gate enforces)."""
    return _apply_preagg_u32_core(state, buf, ring=ring, dump_row=dump_row)


def _apply_preagg_u32_core(state, buf, *, ring, dump_row):
    pair = lax.shift_right_logical(buf, jnp.int32(12))  # bit pattern, not sign
    cnt = buf & jnp.int32(0xFFF)
    # real entries always have count < 0xFFF (host gate); the padding
    # word 0xFFFFFFFF decodes to count 0xFFF — so the count field alone
    # distinguishes padding even when the pair domain fills 2^20
    ok = (cnt != 0xFFF) & (pair < dump_row * ring)
    p = jnp.where(ok, pair, 0)
    rows = jnp.where(ok, p // ring, dump_row).astype(jnp.int32)
    cols = (p % ring).astype(jnp.int32)
    return PaneState(sums=state.sums, maxs=state.maxs, mins=state.mins,
                     counts=state.counts.at[rows, cols].add(
                         jnp.where(ok, cnt, 0)))


def apply_preagg_i32_kernel(
    state: PaneState,
    buf: jax.Array,        # (P, 2 + sum_width) int32:
                           # [pair, count, f32-bitcast sum lanes...]
    *,
    sum_width: int,
    ring: int,
    dump_row: int,
) -> PaneState:
    """``apply_preagg_u16_kernel`` with per-pair pre-combined SUM lanes
    (sum/avg aggregates whose lanes are identity lifts — see
    LaneAggregate.sum_fields). Pair < 0 marks padding."""
    pair = buf[:, 0]
    ok = pair >= 0
    p = jnp.where(ok, pair, 0)
    rows = jnp.where(ok, p // ring, dump_row).astype(jnp.int32)
    cols = (p % ring).astype(jnp.int32)
    cnt = jnp.where(ok, buf[:, 1], 0)
    counts = state.counts.at[rows, cols].add(cnt)
    sums = state.sums
    if sum_width:
        lanes = lax.bitcast_convert_type(buf[:, 2:2 + sum_width], jnp.float32)
        lanes = jnp.where(ok[:, None], lanes, 0.0)
        sums = sums.at[rows, cols].add(lanes)
    return PaneState(sums=sums, maxs=state.maxs, mins=state.mins,
                     counts=counts)


def preagg_combine(
    slots: np.ndarray, cols: np.ndarray, valid: np.ndarray,
    data: Dict[str, np.ndarray], sum_fields: Tuple[str, ...],
    *, ring: int, domain: int,
) -> Tuple[np.ndarray, np.ndarray, List[np.ndarray]]:
    """Host half: combine one batch per (slot, ring column) pair.
    Returns (pair ids, counts, per-lane pre-summed f32 arrays)."""
    pk = (slots[valid] * ring + cols[valid]).astype(np.int32)
    # compact to observed pairs (O(nv log nv)) — a dense
    # minlength=domain histogram would allocate and zero O(domain)
    # per batch, which at the 2^23 eligibility bound dwarfs the h2d
    # bytes this path exists to save
    pairs, inv, cnts = np.unique(pk, return_inverse=True,
                                 return_counts=True)
    lanes = []
    for f in sum_fields:
        acc = np.zeros(len(pairs), np.float64)
        np.add.at(acc, inv, np.asarray(data[f], np.float64)[valid])
        lanes.append(acc.astype(np.float32))
    return pairs, cnts, lanes


def preagg_encode_u16(pairs: np.ndarray, cnts: np.ndarray,
                      cap: int) -> np.ndarray:
    """(pairs, counts) → one (cap, 3) uint16 buffer (ONE h2d transfer;
    a second buffer pays a second round trip). Padding rows carry the
    0xFFFF/0xFFFF sentinel pair."""
    n = len(pairs)
    buf = np.empty((cap, 3), np.uint16)
    pu = pairs.astype(np.uint32)
    buf[:n, 0] = pu & 0xFFFF
    buf[:n, 1] = pu >> 16
    buf[:n, 2] = cnts.astype(np.uint16)
    buf[n:] = 0xFFFF
    return buf


def preagg_encode_i32(pairs: np.ndarray, cnts: np.ndarray,
                      lanes: List[np.ndarray], cap: int) -> np.ndarray:
    """(pairs, counts, sum lanes) → one (cap, 2+W) int32 buffer with
    f32 lanes bitcast into the int columns. Padding pair = -1."""
    n = len(pairs)
    buf = np.empty((cap, 2 + len(lanes)), np.int32)
    buf[:n, 0] = pairs
    buf[n:, 0] = -1
    buf[:n, 1] = cnts
    buf[n:, 1] = 0
    for i, ln in enumerate(lanes):
        buf[:n, 2 + i] = ln.view(np.int32)
        buf[n:, 2 + i] = 0
    return buf


def fire_kernel(
    state: PaneState,
    end_panes: jax.Array,  # (W,) int64 global pane ids (window end, exclusive)
    w_valid: jax.Array,    # (W,) bool
    pane_lo: jax.Array,    # scalar int64: oldest written-and-uncleared pane
    pane_hi: jax.Array,    # scalar int64: newest written pane
    *,
    panes_per_window: int,
    ring: int,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Evaluate every (key, fireable-window) pair at once.

    Returns (sums (rows,W,sw), maxs, mins, counts (rows,W)) — the lane
    reduction over each window's pane span. ref role: WindowOperator.
    onEventTime → emitWindowContents, for all keys in one shot.

    The [pane_lo, pane_hi] range masks ring aliasing: a window's pane that
    was never written (or already purged) may share a ring column with a
    newer pane; such cells read as identity. The ingest-side ring guard
    ensures at most one live pane per column within the range.
    """
    ppw = panes_per_window
    want = end_panes[:, None] - ppw + jnp.arange(ppw)[None, :]            # (W, ppw) global panes
    live = (want >= pane_lo) & (want <= pane_hi)                           # (W, ppw)
    rows_n = state.counts.shape[0]
    W = end_panes.shape[0]
    # (W, ring) column-selection mask instead of a per-(window, pane)
    # GATHER: arr[:, ring_ix] gathers rows × W × ppw elements, which XLA
    # lowers at ~20ms per million on TPU (measured — the single hottest
    # op of the fire path); the mask form is a broadcast + reduce the
    # fuser streams at memory bandwidth. Within [pane_lo, pane_hi] at
    # most one live pane occupies a column (the ingest ring guard), so
    # a window's reduction over its live COLUMNS equals the reduction
    # over its live panes.
    colmask = jnp.any(
        ((want % ring)[:, :, None] == jnp.arange(ring)[None, None, :])
        & live[:, :, None], axis=1)                                        # (W, ring)

    def lane_red(arr, red, identity):
        # None lanes (zero declared width) reduce to a zero-width
        # INTERNAL value — never a runtime buffer, so free
        if arr is None:
            return jnp.zeros((rows_n, W, 0), jnp.float32)
        m = colmask[None, :, :, None]
        return red(jnp.where(m, arr[:, None, :, :], identity), axis=2)

    # SUM lanes ride matmuls over the column mask — the MXU does the
    # window reduction without materializing the (rows, W, ring)
    # broadcast the mask-reduce form needs (33 MB per fire at Q5 shape).
    # Counts take the ring-axis prefix-sum path below instead.
    sel_t = colmask.astype(jnp.float32).T                                  # (ring, W)
    if state.sums is None:
        sums = jnp.zeros((rows_n, W, 0), jnp.float32)
    else:
        # f32 matmul accumulates the same f32 lane data the mask-reduce
        # form summed — identical precision class
        sums = jnp.einsum("rcs,cw->rws", state.sums, sel_t)
    maxs = lane_red(state.maxs, jnp.max, -jnp.inf)
    mins = lane_red(state.mins, jnp.min, jnp.inf)
    # COUNTS ride ring-axis PREFIX SUMS: roll the ring so column j
    # holds pane (pane_lo + j), cumsum along the ring, and each
    # window's count is one prefix difference. Integer prefix sums are
    # exact, and every column outside the live [pane_lo, pane_hi] span
    # is provably ZERO (purged panes are cleared, unwritten panes never
    # incremented — the same ring-aliasing invariant the mask form
    # relied on), so out-of-range prefixes contribute nothing. Measured
    # 0.3ms/fire at the 2^22 Q5 shape where a dot over the column mask
    # (f32, f64, or mask-reduce alike) costs ~42ms in composition with
    # the ingest segment_sum.
    roll_amt = (pane_lo % ring).astype(jnp.int32)
    rolled = jnp.roll(state.counts, -roll_amt, axis=1)
    cs = jnp.cumsum(rolled, axis=1)                                        # (rows, ring)
    e_hi = jnp.clip(end_panes - 1 - pane_lo, -1, ring - 1).astype(jnp.int32)
    e_lo = jnp.clip(end_panes - ppw - 1 - pane_lo, -1,
                    ring - 1).astype(jnp.int32)
    hiv = jnp.where(e_hi[None, :] >= 0,
                    jnp.take(cs, jnp.clip(e_hi, 0, ring - 1), axis=1), 0)
    lov = jnp.where(e_lo[None, :] >= 0,
                    jnp.take(cs, jnp.clip(e_lo, 0, ring - 1), axis=1), 0)
    counts = jnp.where(w_valid[None, :], hiv - lov, 0)
    return sums, maxs, mins, counts


_END_SENTINEL = np.int64(-(2**62))  # pads the window axis in fire params


def _unpack_fire_params(params: jax.Array):
    """One packed i64 operand per fire — [pane_lo, pane_hi, anchor,
    end_pane...] with sentinel-padded ends — instead of five separate
    host→device transfers (each pays a transport round trip)."""
    pane_lo = params[0]
    pane_hi = params[1]
    anchor = params[2]
    end_panes = params[3:]
    w_valid = end_panes > _END_SENTINEL // 2
    return pane_lo, pane_hi, anchor, end_panes, w_valid


def fire_pack_kernel(
    state: PaneState,
    params: jax.Array,      # packed: see _unpack_fire_params
    used_mask: jax.Array,   # (rows,) bool — registered-key rows
    *,
    agg: LaneAggregate,
    panes_per_window: int,
    ring: int,
    out_cap: int,
    packed2: bool = False,
) -> jax.Array:
    """fire + select + finalize + COMPACT entirely on device, packed
    into ONE int32 buffer so the host pays exactly one transfer per
    firing advance. The device→host transfer is the throughput ceiling
    of the emit path (bytes × link bandwidth + per-fetch latency), so
    the buffer holds only the fired (key, window) rows — ``out_cap`` of
    them, a host-chosen bound ≥ registered keys × windows, which can
    therefore never truncate — not the full slots × windows grid:
    row 0 = [n, 0, ...]; rows 1..n = [slot_row, end_pane delta vs
    pane_lo, count, f32-bitcast result lanes...] with result columns in
    sorted-field order.

    ref role: the whole onEventTime → emitWindowContents →
    Collector.collect chain, batched."""
    pane_lo, pane_hi, _anchor, end_panes, w_valid = _unpack_fire_params(params)
    sums, maxs, mins, counts = fire_kernel(
        state, end_panes, w_valid, pane_lo, pane_hi,
        panes_per_window=panes_per_window, ring=ring)
    rows = counts.shape[0]
    W = end_panes.shape[0]
    nz = (counts > 0) & used_mask[:, None] & w_valid[None, :]
    flat = nz.reshape(-1)
    k = rows * W
    # stable-argsort compaction instead of jnp.nonzero — identical
    # semantics (selected indices in row-major order, k-padded), but
    # sorts run ~0.2ms/M on TPU while nonzero's lowering measured ~40ms
    m = min(k, out_cap)
    idx = jnp.argsort(~flat, stable=True)[:m]
    idx = jnp.where(flat[idx], idx, k)
    if m < out_cap:
        idx = jnp.concatenate([idx, jnp.full(out_cap - m, k, idx.dtype)])
    row = jnp.minimum(idx // W, rows - 1).astype(jnp.int32)
    wi = (idx % W).astype(jnp.int32)
    sel_counts = jnp.where(idx < k, counts[row, wi], 0)
    res = agg.finalize(sums[row, wi], maxs[row, wi], mins[row, wi], sel_counts)
    end_delta = (end_panes[wi] - pane_lo).astype(jnp.int32)
    if packed2:
        # count-only 2-column layout: (row << 8 | delta, count) — 8
        # bytes/row instead of 12; valid when the op's static shape
        # bounds fit (slots < 2^23, delta < 2^8 — see _fire_packed2).
        # Egress bytes are the WordCount-family wall, and the transfer
        # cost is pure host/link budget on the remote-attached chip.
        cols = [(row << 8) | end_delta, sel_counts.astype(jnp.int32)]
    else:
        cols = [row, end_delta, sel_counts.astype(jnp.int32)]
    for name in ([] if packed2 else sorted(res)):
        if name == "count":
            continue  # column 2 already carries it — for count-only
            # aggregates (WordCount) this is 25% of the egress bytes
        v = res[name].reshape(out_cap)
        if jnp.issubdtype(v.dtype, jnp.integer):
            # integer result lanes (counts) stay exact i32; float lanes
            # ride as f32 bitcasts (decode reads the dtype probe)
            cols.append(v.astype(jnp.int32))
        else:
            cols.append(lax.bitcast_convert_type(v.astype(jnp.float32), jnp.int32))
    body = jnp.stack(cols, axis=1)                       # (out_cap, C)
    head = jnp.zeros((1, body.shape[1]), jnp.int32).at[0, 0].set(
        jnp.sum(flat).astype(jnp.int32))
    return jnp.concatenate([head, body])                 # (out_cap+1, C)


def _topn_select_append(
    emit_ring: jax.Array,
    sums, maxs, mins, counts,
    nz: jax.Array,          # (rows, W) candidate mask
    v: jax.Array,           # (rows, W) ranking values (-inf non-candidates)
    thresh: jax.Array,      # (W,) n-th value per window (may be -inf)
    end_panes: jax.Array,
    anchor,
    *,
    agg: LaneAggregate,
    sel_cap: int,
    row_offset,             # scalar added to row ids (device block base)
) -> jax.Array:
    """Shared tail of both top-n fire kernels (local + mesh): select
    rows at/above the per-window threshold (ties kept; -inf thresh ⇒
    all candidates), finalize lanes, append winners to the emit ring.
    Head col 0 = monotone appended total; col 1 accumulates rows
    TRUNCATED by sel_cap (tie explosion) — drain_ring raises on it, the
    loud-overflow contract."""
    rows, W = counts.shape
    sel = nz & (v >= thresh[None, :])
    flat = sel.reshape(-1)
    K = rows * W
    # compact via a stable ARGSORT of the negated mask instead of
    # jnp.nonzero: sorts run ~0.2ms per million on TPU while nonzero's
    # lowering measured ~40ms per fire; the first sel_cap positions are
    # exactly the selected indices in row-major order
    m = min(K, sel_cap)
    idx = jnp.argsort(~flat, stable=True)[:m]
    idx = jnp.where(flat[idx], idx, K)
    if m < sel_cap:  # tiny grids: pad to the fixed selection shape
        idx = jnp.concatenate([idx, jnp.full(sel_cap - m, K, idx.dtype)])
    row = jnp.minimum(idx // W, rows - 1).astype(jnp.int32)
    wi = (idx % W).astype(jnp.int32)
    total_sel = jnp.sum(flat).astype(jnp.int32)
    n = jnp.minimum(total_sel, sel_cap)
    sel_counts = jnp.where(idx < K, counts[row, wi], 0)
    res_sel = agg.finalize(sums[row, wi], maxs[row, wi], mins[row, wi], sel_counts)
    end_delta = (end_panes[wi] - anchor).astype(jnp.int32)
    cols = [row + row_offset, end_delta, sel_counts.astype(jnp.int32)]
    for name in sorted(res_sel):
        if name == "count":
            continue  # column 2 already carries it (see fire_pack_kernel)
        u = res_sel[name].reshape(sel_cap)
        if jnp.issubdtype(u.dtype, jnp.integer):
            cols.append(u.astype(jnp.int32))
        else:
            cols.append(lax.bitcast_convert_type(u.astype(jnp.float32), jnp.int32))
    body = jnp.stack(cols, axis=1)                         # (sel_cap, C)
    row_cap = emit_ring.shape[0] - 2
    total = emit_ring[0, 0]
    ar = jnp.arange(sel_cap)
    pos = (total + ar) % row_cap + 1
    safe_pos = jnp.where(ar < n, pos, row_cap + 1)         # dump row
    out = emit_ring.at[safe_pos].set(body)
    return out.at[0, 0].add(n).at[0, 1].add(total_sel - n)


def ring_append_topn_kernel(
    state: PaneState,
    emit_ring: jax.Array,   # (row_cap + 2, C) i32: row 0 = [total, ...],
                            # rows 1..row_cap = data ring, last row = dump
    params: jax.Array,      # packed: see _unpack_fire_params
    used_mask: jax.Array,
    *,
    agg: LaneAggregate,
    panes_per_window: int,
    ring: int,
    sel_cap: int,
    by: str,
    topn: int,
) -> jax.Array:
    """Top-n fire that APPENDS winners to a device-resident emit ring
    instead of returning a fresh buffer. The host polls the ring — one
    fixed-shape array whose row 0 carries a monotone total-appended
    counter — at its own cadence, so N watermark advances cost ONE
    device→host fetch and zero per-fire transfers. This is the emit
    architecture for transports where a device→host read pays a large
    fixed latency (and starves under concurrent ingest): results stay
    in HBM until the host opens a quiet window.

    Overflow (appends since last poll > row_cap) is detected host-side
    from the counter, never silent. ref role: RecordWriter's buffer ring
    + PipelinedSubpartition, collapsed into device memory."""
    pane_lo, pane_hi, anchor, end_panes, w_valid = _unpack_fire_params(params)
    return _ring_append_topn_core(
        state, emit_ring, pane_lo, pane_hi, anchor, end_panes, w_valid,
        used_mask, agg=agg, panes_per_window=panes_per_window, ring=ring,
        sel_cap=sel_cap, by=by, topn=topn)


def _ring_append_topn_core(
    state, emit_ring, pane_lo, pane_hi, anchor, end_panes, w_valid,
    used_mask, *, agg, panes_per_window, ring, sel_cap, by, topn,
):
    sums, maxs, mins, counts = fire_kernel(
        state, end_panes, w_valid, pane_lo, pane_hi,
        panes_per_window=panes_per_window, ring=ring)
    rows = counts.shape[0]
    nz = (counts > 0) & used_mask[:, None] & w_valid[None, :]
    res = agg.finalize(sums, maxs, mins, counts)
    v = jnp.where(nz, res[by].astype(jnp.float32), -jnp.inf)
    k = min(topn, rows)
    topv = lax.top_k(v.T, k)[0]
    # thresh = -inf when a window has fewer than n candidates (top_k
    # pads with -inf); nz already excludes non-candidates, so
    # v >= -inf correctly selects ALL of that window's real rows
    thresh = topv[:, k - 1]
    return _topn_select_append(
        emit_ring, sums, maxs, mins, counts, nz, v, thresh,
        end_panes, anchor, agg=agg, sel_cap=sel_cap,
        row_offset=jnp.int32(0))


# fused-step header layout, in i32 words:
# [0:2]=pane_lo i64, [2:4]=pane_hi i64, [4:6]=anchor i64,
# [6]=unused, [7]=clear-mask bits (ring<=32), [8:8+MIN_FIRE_PAD]=window-
# end deltas vs pane_lo (sentinel INT32_MIN = padding), then at
# DEVGEN_HDR_OFF the device-generator params (batch index, dead_below,
# refire_below as i64), zero pad to FUSED_HDR — the
# header upload must stay ABOVE the transport's tiny-transfer stall
# threshold (~100 bytes measured); 128 words = 512 bytes
FUSED_HDR = 128
_DELTA_SENTINEL = -(2**30)
# fire params are sentinel-padded to at least this many window ends in
# the HEADER (sub-100-byte uploads hit the transport's tiny-transfer
# stall — see clear_kernel); the KERNEL reads only its static fire_pad
# prefix of them (pow2-bucketed to the real end count, _fire_pad_bucket)
MIN_FIRE_PAD = 64


def fused_step_kernel(
    state: PaneState,
    emit_ring: jax.Array,
    buf: jax.Array,        # (FUSED_HDR + P,) int32: header + u32 pairs
    used_mask: jax.Array,
    *,
    agg: LaneAggregate,
    panes_per_window: int,
    ring: int,
    sel_cap: int,
    by: str,
    topn: int,
    dump_row: int,
    fire_gate: bool = False,
    fire_pad: int = MIN_FIRE_PAD,
) -> Tuple[PaneState, jax.Array, jax.Array]:
    """ONE device dispatch per microbatch: pre-aggregated apply +
    watermark fire (top-n ring append) + pane clear, with the fire
    parameters riding in the SAME upload as the pair list. On the
    measured transport each executable launch and each transfer carries
    tens of ms of in-situ overhead — the fusion collapses per-batch
    stream traffic to one upload + one launch (+ the cadenced ring
    announce); an A/B against a split header + stash-time pair upload
    measured WORSE (two transfer ops beat one combined even with
    overlap). ref: 4.B/4.D hot paths, dispatched as one program.

    Third output: the emit ring's HEAD ROW after this step's fire —
    the piggybacked readiness/ring-header token (announced at dispatch;
    the throttle consumes it instead of is_ready-probing, and its
    [total, truncated] words stand in for a ring-header poll)."""
    hdr = buf[:FUSED_HDR]
    pairs = buf[FUSED_HDR:]
    state = _apply_preagg_u32_core(
        state, pairs, ring=ring, dump_row=dump_row)
    state, emit_ring = _fused_fire_clear(
        state, emit_ring, hdr, used_mask, agg=agg,
        panes_per_window=panes_per_window, ring=ring, sel_cap=sel_cap,
        by=by, topn=topn, fire_gate=fire_gate, fire_pad=fire_pad)
    return state, emit_ring, emit_ring[0]


def _hdr_i64(hdr: jax.Array, i: int) -> jax.Array:
    return lax.bitcast_convert_type(
        hdr[i:i + 2].reshape(1, 2), jnp.int64)[0]


def _fused_fire_clear(state, emit_ring, hdr, used_mask, *, agg,
                      panes_per_window, ring, sel_cap, by, topn,
                      fire_gate=False, fire_pad=MIN_FIRE_PAD):
    """Shared fire + clear tail of the one-dispatch step kernels: the
    fire parameters and the purge mask ride the FUSED_HDR header.

    ``fire_gate`` (pipeline.fire-gate, PROFILE.md §12): the fire/top-n/
    ring-append subgraph — whose stable argsort + top_k IS the CPU step
    cost and was measured on every dispatch whether or not any window
    fires (§8.6) — runs under a ``lax.cond`` keyed on the header's
    window-end list, and the pane purge under a second cond keyed on
    the clear words. The host fills both header fields before dispatch
    (``_fused_fill_header``), so a non-firing sub-batch skips the sort
    entirely. Byte-identical by construction: with no valid ends the
    ungated core selects zero rows and leaves ring bytes and head
    counters unchanged, and a zero clear mask is the identity — the
    cond only skips provably-no-op work. fire_gate=False is the exact
    pre-gate graph.

    ``fire_pad``: how many of the header's MIN_FIRE_PAD window-end
    slots this program READS — the static width of the whole fire
    subgraph (fire_kernel's rows×W reductions, the rows×W selection
    argsort, the W-way top_k). The host buckets it to the next power
    of two ≥ the sub-batch's real end count (``_fire_pad_bucket``), so
    K sub-batch dispatches of ~W/K ends each cost ≈ ONE W-wide fire —
    without this, every dispatch paid the full 64-wide subgraph and
    sub-batching traded throughput ∝ K for its p99 win (§8.6's
    measured tax). Sentinel-padded slots never select rows, so the
    bucket width never changes bytes, only skipped work."""
    pane_lo = _hdr_i64(hdr, 0)
    pane_hi = _hdr_i64(hdr, 2)
    anchor = _hdr_i64(hdr, 4)
    clear_lo = hdr[7]
    clear_hi = hdr[6]
    deltas = hdr[8:8 + fire_pad]
    w_valid = deltas > _DELTA_SENTINEL
    end_panes = jnp.where(w_valid, pane_lo + deltas.astype(jnp.int64),
                          _END_SENTINEL)

    def _fire(ring_in):
        return _ring_append_topn_core(
            state, ring_in, pane_lo, pane_hi, anchor, end_panes, w_valid,
            used_mask, agg=agg, panes_per_window=panes_per_window,
            ring=ring, sel_cap=sel_cap, by=by, topn=topn)

    if fire_gate:
        emit_ring = lax.cond(jnp.any(w_valid), _fire,
                             lambda ring_in: ring_in, emit_ring)
    else:
        emit_ring = _fire(emit_ring)
    # 64-bit clear mask split over header words [7] (columns 0-31)
    # and [6] (columns 32-63) — rings up to 64 stay on the one-dispatch
    # fused paths (a 2^22-record batch's event span outgrows 32)
    cm = (lax.shift_right_logical(
        clear_lo, jnp.arange(min(ring, 32), dtype=jnp.int32))
        & jnp.int32(1)) != 0
    if ring > 32:
        cm_hi = (lax.shift_right_logical(
            clear_hi, jnp.arange(min(ring - 32, 32), dtype=jnp.int32))
            & jnp.int32(1)) != 0
        cm = jnp.concatenate([cm, cm_hi])
    if ring > 64:
        cm = jnp.concatenate([cm, jnp.zeros(ring - 64, bool)])
    if fire_gate:
        state = lax.cond(
            (clear_lo != 0) | (clear_hi != 0),
            lambda s: clear_kernel(s, cm.astype(jnp.int32)),
            lambda s: s, state)
    else:
        state = clear_kernel(state, cm.astype(jnp.int32))
    return state, emit_ring


# refire-candidate bitmap span of the device-generator step (panes
# above dead_below); configs whose lateness span exceeds this fall back
# to the host ingest path
DEVGEN_REFIRE_BITS = 2048


def devgen_step_kernel(
    state: PaneState,
    emit_ring: jax.Array,
    buf: jax.Array,        # (FUSED_HDR,) int32 header ONLY — no pairs
    used_mask: jax.Array,
    *,
    gen,                   # traceable (batch_index i64) -> (keys, ts)
    key_domain: int,       # keys [0, key_domain) map to slot == key
    agg: LaneAggregate,
    panes_per_window: int,
    ring: int,
    sel_cap: int,
    by: str,
    topn: int,
    dump_row: int,
    pane_ms: int,
    offset_ms: int,
    fire_gate: bool = False,
    fire_pad: int = MIN_FIRE_PAD,
) -> Tuple[PaneState, jax.Array, jax.Array]:
    """Device-chained generator ingest: ONE dispatch synthesizes the
    microbatch ON DEVICE, maps keys to slots, segment-sums the panes,
    fires and clears — zero per-record host work and zero record bytes
    on the link. This is the chained-source pattern taken to its TPU
    conclusion (ref: operator chaining elides serialization between
    chained operators — SURVEY §3.2; flink-connector-datagen as the
    embedded source): the source lives INSIDE the window operator's
    step program.

    Key→slot is the DENSE IDENTITY map over the source's declared
    bounded key domain (KeyDirectory.register_dense): slot must be a
    pure function of key on device because every alternative measured
    pathological on this hardware — XLA lowers large gathers at ~20ms
    per million elements and a 1M-index scatter in SECONDS, while
    sort/cumsum/segment primitives run ~0.2ms per million. Records
    outside the domain are EXCLUDED from the apply and counted in the
    stats output; the host re-synthesizes the batch bit-exactly (the
    generator contract), registers the new keys, and applies just those
    records through the pair path. The third output is an int32 stats
    vector: [n_valid, n_late, n_miss, ring_total, n_refire,
    ring_truncated, 0, 0] ++ refire-candidate bitmap over panes
    [dead_below, dead_below + DEVGEN_REFIRE_BITS) — words 3/5 carry
    the emit ring's POST-FIRE head counters, so the announced stats
    copy doubles as the piggybacked readiness token AND a ring-header
    poll (no separate fetch; PROFILE.md §12)."""
    hdr = buf[:FUSED_HDR]
    batch_index = _hdr_i64(hdr, DEVGEN_HDR_OFF)
    dead_below = _hdr_i64(hdr, DEVGEN_HDR_OFF + 2)
    refire_below = _hdr_i64(hdr, DEVGEN_HDR_OFF + 4)
    keys, ts = gen(batch_index)
    hit = (keys >= 0) & (keys < key_domain)
    slot = jnp.where(hit, keys, jnp.int64(0))
    pane = (ts - offset_ms) // pane_ms           # floor div
    late = hit & (pane < dead_below)
    miss = ~hit
    valid = hit & ~late
    col = pane % ring                            # sign of divisor: >= 0
    # flat segment-sum, NOT a 2D scatter: XLA lowers a 1M-index
    # scatter-add serially on TPU (measured seconds/step) while
    # segment_sum over the flat pane domain runs ~0.2ms per million
    n_rows = state.counts.shape[0]               # layout slots + dump
    flat = jnp.where(valid, slot * ring + col,
                     jnp.int64(dump_row * ring)).astype(jnp.int32)
    inc = jax.ops.segment_sum(
        jnp.ones(flat.shape[0], state.counts.dtype), flat,
        num_segments=n_rows * ring)
    state = PaneState(sums=state.sums, maxs=state.maxs, mins=state.mins,
                      counts=state.counts + inc.reshape(n_rows, ring))
    refire = valid & (pane < refire_below)
    roff = jnp.where(refire, pane - dead_below,
                     DEVGEN_REFIRE_BITS).astype(jnp.int32)
    rbm = jax.ops.segment_sum(
        jnp.ones_like(roff), roff,
        num_segments=DEVGEN_REFIRE_BITS + 1)[:DEVGEN_REFIRE_BITS]
    # materialize the ingest before the fire reads it: without the
    # barrier XLA fuses the segment_sum into the fire path's many
    # reads of counts and re-evaluates it per read (measured 170ms vs
    # 0.2ms for the ingest alone)
    state = PaneState(
        sums=state.sums, maxs=state.maxs, mins=state.mins,
        counts=lax.optimization_barrier(state.counts))
    state, emit_ring = _fused_fire_clear(
        state, emit_ring, hdr, used_mask, agg=agg,
        panes_per_window=panes_per_window, ring=ring, sel_cap=sel_cap,
        by=by, topn=topn, fire_gate=fire_gate, fire_pad=fire_pad)
    # stats words 3/5 = the POST-FIRE ring head [total, truncated]:
    # the one announced copy carries ingest accounting, step readiness,
    # AND the ring header in a single transfer
    stats = jnp.concatenate([
        jnp.stack([valid.sum().astype(jnp.int32),
                   late.sum().astype(jnp.int32),
                   miss.sum().astype(jnp.int32),
                   emit_ring[0, 0],
                   refire.sum().astype(jnp.int32),
                   emit_ring[0, 1],
                   jnp.int32(0), jnp.int32(0)]).astype(jnp.int32),
        (rbm > 0).astype(jnp.int32)])
    return state, emit_ring, stats


_JIT_FUSED_STEP = jax.jit(
    fused_step_kernel,
    static_argnames=("agg", "panes_per_window", "ring", "sel_cap", "by",
                     "topn", "dump_row", "fire_gate", "fire_pad"),
    donate_argnums=(0,))
_JIT_DEVGEN_STEP = jax.jit(
    devgen_step_kernel,
    static_argnames=("gen", "key_domain", "agg", "panes_per_window",
                     "ring", "sel_cap", "by", "topn", "dump_row",
                     "pane_ms", "offset_ms", "fire_gate", "fire_pad"),
    donate_argnums=(0,))


def clear_kernel(state: PaneState, clear_mask: jax.Array) -> PaneState:
    """Reset ring columns selected by clear_mask to identities (ref
    role: WindowOperator.clearAllState / registerCleanupTimer).

    ``clear_mask`` is int32, padded to >=64 elements: uploads under
    ~100 bytes hit a pathological fixed stall (~67ms/step measured) on
    the remote-attached transport, and the ring is often 16 columns.
    Only the first ``ring`` entries are meaningful."""
    ring = state.counts.shape[1]
    cm = clear_mask[:ring] != 0
    m3 = cm[None, :, None]
    m2 = cm[None, :]

    def cl(arr, fill):
        return None if arr is None else jnp.where(m3, fill, arr)

    return PaneState(
        sums=cl(state.sums, 0.0),
        maxs=cl(state.maxs, -jnp.inf),
        mins=cl(state.mins, jnp.inf),
        counts=jnp.where(m2, 0, state.counts),
    )


# state is donated: each microbatch's update reuses the previous state's
# HBM buffers in place instead of allocating four fresh tensors (the
# caller always rebinds ``self.state = apply(self.state, ...)``, and
# checkpoint snapshots copy to host eagerly, so no stale reference ever
# reads a donated buffer)
_JIT_APPLY = jax.jit(
    apply_kernel,
    static_argnames=("agg", "ring", "dump_row"),
    donate_argnums=(0,))
_JIT_APPLY_SPLIT = jax.jit(
    apply_kernel_split,
    static_argnames=("agg", "dump_row"),
    donate_argnums=(0,))
_JIT_PREAGG_U16 = jax.jit(
    apply_preagg_u16_kernel,
    static_argnames=("ring", "dump_row"),
    donate_argnums=(0,))
_JIT_PREAGG_U32 = jax.jit(
    apply_preagg_u32_kernel,
    static_argnames=("ring", "dump_row"),
    donate_argnums=(0,))
_JIT_PREAGG_I32 = jax.jit(
    apply_preagg_i32_kernel,
    static_argnames=("sum_width", "ring", "dump_row"),
    donate_argnums=(0,))
_JIT_FIRE_PACK = jax.jit(
    fire_pack_kernel,
    static_argnames=("agg", "panes_per_window", "ring", "out_cap",
                     "packed2"))
# NOTE: emit_ring is NOT donated — the drain thread may be fetching the
# previous ring array concurrently with the next append dispatch, and
# donation would delete the buffer under that read. The append copies
# the (small, fixed) ring on device instead.
_JIT_RING_TOPN = jax.jit(
    ring_append_topn_kernel,
    static_argnames=("agg", "panes_per_window", "ring", "sel_cap", "by", "topn"))
_JIT_CLEAR = jax.jit(clear_kernel, donate_argnums=(0,))


def ring_remap_kernel(state: PaneState, src: jax.Array,
                      keep: jax.Array) -> PaneState:
    """Move every live pane column old→new when the pane ring is
    resized: new column j takes old column src[j] where keep[j], else
    the identity fill. Module-level jit so a growth (rare but on the
    latency path) compiles once per (old_ring, new_ring) shape pair per
    process, not once per growth event."""

    def cols(arr, fill):
        if arr is None:
            return None
        g = arr[:, src]
        m = keep[None, :, None] if g.ndim == 3 else keep[None, :]
        return jnp.where(m, g, fill)

    return PaneState(
        sums=cols(state.sums, 0.0),
        maxs=cols(state.maxs, -jnp.inf),
        mins=cols(state.mins, jnp.inf),
        counts=cols(state.counts, 0),
    )


# no donation: the remapped output has a different ring width than the
# input, so XLA could never reuse the buffers anyway (it would only warn)
_JIT_RING_REMAP = jax.jit(ring_remap_kernel)

# catch-up fires are evaluated in chunks of this many windows so they
# reuse the steady-state compiled kernels (pow2 pads: 1,2,4) and keep
# each packed buffer bounded — device→host bandwidth is the emit ceiling
# and chunked buffers still fetch together in one round trip
MAX_FIRE_CHUNK = 4
# the ring/top-n path appends in HBM (no per-fire fetch buffer), so it
# takes a steady advance's whole window list in ONE dispatch
MAX_FIRE_CHUNK_RING = 16
# devgen header params (batch_index, dead_below, refire_below as i64)
# start right after the fire-delta region; must stay inside FUSED_HDR
DEVGEN_HDR_OFF = 8 + MIN_FIRE_PAD
assert DEVGEN_HDR_OFF + 6 <= FUSED_HDR


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


# ---------------------------------------------------------------------------
# Planning: static layout from assigner + timing characteristics.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class WindowPlan:
    pane_ms: int
    offset_ms: int
    size_ms: int
    slide_ms: int
    panes_per_window: int
    panes_per_slide: int
    ring: int
    allowed_lateness_ms: int

    @classmethod
    def plan(
        cls,
        assigner: WindowAssigner,
        *,
        allowed_lateness_ms: int = 0,
        max_out_of_orderness_ms: int = 0,
        headroom_panes: int = 4,
    ) -> "WindowPlan":
        pane = assigner.pane_ms
        # Live pane span: a pane stays until wm >= pane_start + size +
        # lateness; the newest writable pane is at max_ts = wm + delay.
        # headroom covers event time running ahead of the watermark clock
        # between advances (one microbatch's worth of time progress).
        span_ms = assigner.size_ms + allowed_lateness_ms + max_out_of_orderness_ms
        ring = -(-span_ms // pane) + 1 + headroom_panes
        if ring > 65536:
            raise ValueError(
                f"pane ring of {ring} panes (pane={pane}ms from gcd(size={assigner.size_ms},"
                f" slide={assigner.slide_ms})) is degenerate — choose a slide that divides"
                " the window size (or shares a larger common divisor)")
        return cls(
            pane_ms=pane,
            offset_ms=assigner.offset_ms,
            size_ms=assigner.size_ms,
            slide_ms=assigner.slide_ms,
            panes_per_window=assigner.panes_per_window,
            panes_per_slide=assigner.panes_per_slide,
            ring=ring,
            allowed_lateness_ms=allowed_lateness_ms,
        )

    def pane_of(self, ts: np.ndarray) -> np.ndarray:
        return (ts - self.offset_ms) // self.pane_ms

    def window_end_ms(self, end_pane: int) -> int:
        return int(end_pane) * self.pane_ms + self.offset_ms

    def window_dead(self, end_pane: int, wm: int) -> bool:
        """A window is dead (late beyond lateness) iff
        window.maxTimestamp() + allowedLateness <= watermark
        (ref: WindowOperator.isWindowLate / isCleanupTime)."""
        end_ms = end_pane * self.pane_ms + self.offset_ms
        return end_ms - 1 + self.allowed_lateness_ms <= wm

    def first_dead_pane(self, wm: int) -> int:
        """Panes strictly below this are finally purged at watermark wm:
        the LAST window containing the pane is dead. Exact reference
        boundary: ((p//pps)*pps + ppw) is that window's end pane."""
        if wm == LONG_MIN:
            return np.iinfo(np.int64).min // 2
        pps, ppw = self.panes_per_slide, self.panes_per_window
        t = wm + 1 - self.allowed_lateness_ms - self.offset_ms
        q = t // self.pane_ms - ppw
        return (q // pps + 1) * pps

    def fireable_end_panes(
        self, wm_prev: int, wm_now: int, min_pane_seen: Optional[int] = None
    ) -> List[int]:
        """Slide-aligned window end panes e with wm_prev < end-1 <= wm_now
        — the first-time firings this advance unlocks (batched
        EventTimeTrigger: fire iff wm >= window.maxTimestamp).

        min_pane_seen bounds the range at job start (windows entirely
        before the first record are empty and never emit anyway).
        """
        if wm_now == LONG_MIN:
            return []
        pps, ppw = self.panes_per_slide, self.panes_per_window
        # Window STARTS are slide-aligned (multiples of pps), so END panes
        # satisfy e ≡ ppw (mod pps) — not e ≡ 0 unless size % slide == 0.
        def align_down(m: int) -> int:
            return m - ((m - ppw) % pps)

        # window end time must satisfy end - 1 <= wm  => end_ms <= wm + 1
        hi_end = align_down((wm_now + 1 - self.offset_ms) // self.pane_ms)
        if wm_prev == LONG_MIN:
            if min_pane_seen is None:
                return []
            lo_end = align_down(min_pane_seen)
        else:
            lo_end = align_down((wm_prev + 1 - self.offset_ms) // self.pane_ms)
        out = []
        e = lo_end + pps
        while e <= hi_end:
            out.append(int(e))
            e += pps
        return out

    # -- shared host control-plane math (keyed WindowOperator and the
    # global WindowAllOperator both fire with EXACTLY these rules; any
    # semantic fix lands here once) --------------------------------------

    def late_refire_ends(self, late_panes: np.ndarray,
                         fired_below_end: int, wm: int) -> List[int]:
        """Ends of already-fired, still-live windows that a late-within-
        lateness record in ``late_panes`` must re-fire (ref:
        EventTimeTrigger.onElement fires immediately for late elements;
        isWindowLate skips dead windows)."""
        out: List[int] = []
        pps, ppw = self.panes_per_slide, self.panes_per_window
        for p in np.unique(late_panes).tolist():
            # windows containing pane p end at (p//pps)*pps + ppw,
            # stepping down by pps while > p
            e = (p // pps) * pps + ppw
            while e > p:
                if e <= fired_below_end and not self.window_dead(e, wm):
                    out.append(int(e))
                e -= pps
        return out

    def fire_frontier(self, wm: int) -> int:
        """Highest slide-aligned end pane the watermark has passed — the
        fired frontier late records compare against."""
        pps, ppw = self.panes_per_slide, self.panes_per_window
        m = (wm + 1 - self.offset_ms) // self.pane_ms
        return m - ((m - ppw) % pps)

    def last_data_end_ms(self, max_pane_seen: int) -> int:
        """End time (ms) of the last window that can contain data."""
        pps = self.panes_per_slide
        last_end = (max_pane_seen // pps) * pps + self.panes_per_window
        return last_end * self.pane_ms + self.offset_ms

    def enumerate_fire_ends(self, prev_wm: int, wm: int,
                            min_pane_seen: Optional[int],
                            max_pane_seen: Optional[int]) -> List[int]:
        """First-time fireable end panes for a prev_wm → wm advance,
        clamped to windows that can contain data (a big idle jump must
        not enumerate provably-empty windows)."""
        if max_pane_seen is None:
            return []
        ends_wm = min(wm, self.last_data_end_ms(max_pane_seen) - 1)
        if prev_wm != LONG_MIN and prev_wm >= ends_wm:
            return []
        return self.fireable_end_panes(prev_wm, ends_wm, min_pane_seen)

    def final_watermark_for(self, watermark: int,
                            max_pane_seen: Optional[int]) -> int:
        """Watermark completing (and purging) every window that can hold
        data — the end-of-input flush point."""
        if max_pane_seen is None:
            return watermark if watermark != LONG_MIN else 0
        return (self.last_data_end_ms(max_pane_seen)
                + self.allowed_lateness_ms + 1)


# ---------------------------------------------------------------------------
# Host-side operator runtime (single shard range; the sharded pipeline in
# exchange/ reuses the same kernels inside shard_map).
# ---------------------------------------------------------------------------

class WindowOperator:
    """Drives the kernels for one keyed window aggregation.

    Semantics golden-checked against the reference's WindowOperatorTest
    behaviours (ref: flink-streaming-java/src/test/.../windowing/
    WindowOperatorTest.java): event-time firing, allowed lateness with
    late re-firings, late-beyond-lateness side output, purge on cleanup.
    """

    def __init__(
        self,
        assigner: WindowAssigner,
        agg: LaneAggregate,
        *,
        num_shards: int = 128,
        slots_per_shard: int = 1024,
        allowed_lateness_ms: int = 0,
        max_out_of_orderness_ms: int = 0,
        shard_range: Optional[Tuple[int, int]] = None,
        mesh_plan: Optional[MeshPlan] = None,
        exchange_capacity: Optional[int] = None,
        top_n: Optional[Tuple[str, int]] = None,
        spill: bool = False,
        spill_store: Optional[Any] = None,
        exchange_impl: str = "all-to-all",
        host_pool: Optional[Any] = None,
        fold_chunk_records: Optional[int] = None,
        fire_gate: bool = True,
        readiness: str = "piggyback",
    ) -> None:
        self.assigner = assigner
        self.agg = agg
        self.mesh_plan = mesh_plan
        self.exchange_impl = exchange_impl
        # fire-gated dispatch (pipeline.fire-gate, PROFILE.md §12): the
        # fused/devgen step programs run the fire/top-n/ring-append
        # subgraph (and the pane purge) under lax.cond, so a dispatch
        # whose header carries no fireable window end skips the
        # dominant sort instead of paying it every sub-batch (§8.6).
        # False = the exact pre-gate graphs (the A/B axis).
        self.fire_gate = bool(fire_gate)
        # step-readiness plumbing (pipeline.readiness): 'piggyback'
        # derives throttle readiness from a tiny ANNOUNCED per-step
        # output (the devgen stats vector / the fused kernel's ring-head
        # row) — the wait is a consume of an in-flight transfer, never a
        # separate is_ready relay round trip (§8.3 lever a); 'probe' is
        # the legacy is_ready spin on the in-flight marker.
        if readiness not in ("piggyback", "probe"):
            raise ValueError(
                f"pipeline.readiness must be 'piggyback' or 'probe', "
                f"got {readiness!r}")
        self.readiness = readiness
        # piggybacked ring-header knowledge (coalesced readback): tokens
        # carry the emit ring's [total, truncated] head words; once a
        # token AT OR AFTER the last row-carrying fire has landed, an
        # opportunistic drain poll whose known total equals the drained
        # count skips the ring fetch outright (see drain_ring).
        self._token_seq = 0
        self._rowfire_token_seq = 0  # tokens below this predate a fire
        self._ring_head_seq = 0
        self._ring_head_known = False
        self._ring_head_total = 0
        # processing-time mode (ref: TumblingProcessingTimeWindows +
        # ProcessingTimeTrigger + the proc-time half of the timer
        # service): records are stamped with the operator clock at
        # ingest and fires ride advance_processing_time — the SAME pane
        # machinery with the clock as the time axis. No lateness, no
        # out-of-orderness, by construction.
        self.uses_processing_time = bool(
            getattr(assigner, "is_processing_time", False))
        self.clock = None
        if self.uses_processing_time:
            from flink_tpu.time.clock import SystemProcessingTimeService
            self.clock = SystemProcessingTimeService()
            if allowed_lateness_ms:
                raise ValueError(
                    "allowed lateness is event-time-only; processing-"
                    "time windows cannot see late records")
            max_out_of_orderness_ms = 0
        if exchange_capacity is not None and exchange_capacity < 0:
            raise ValueError(
                f"exchange_capacity must be >= 0, got {exchange_capacity}")
        # 0 means auto everywhere (matches the config option), not
        # "capacity zero" — normalize here so direct construction and
        # the Driver path agree
        self.exchange_capacity = exchange_capacity or None
        # (result_field, n): fire only each window's top-n rows by that
        # field (ties kept) — evaluated on device, shrinking the emit
        # transfer to the winners (Q5 hot-items shape)
        self._topn = top_n
        # device-resident emit ring (top-n path): fires append winners in
        # HBM; the host polls one array at its own cadence (see
        # ring_append_topn_kernel). Lazy — shape needs result arity.
        self._emit_ring: Optional[jax.Array] = None
        self._ring_drained = 0
        self._ring_anchor: Optional[int] = None
        # recent ANNOUNCED ring versions as (version_no, array):
        # copy_to_host_async is issued at fire dispatch, and the ring is
        # never donated, so every version stays valid. A periodic drain
        # fetches the newest version whose copy already landed instead
        # of parking on the latest one's still-running compute; rows it
        # misses are monotone-counter rows the next poll picks up. A
        # barrier drain passes min_no (its fire's version) so it can
        # never read a version older than the rows it must deliver.
        self._ring_versions: collections.deque = collections.deque(maxlen=4)
        self._ring_version_no = 0
        # fire-cohort latency bookkeeping (driver emit_latency_ms): a
        # (ring_version, dispatch_stamp) entry per row-carrying fire,
        # popped to _delivered_stamps by the drain_ring call whose
        # fetched version first makes those rows HOST-VISIBLE. The
        # driver records one histogram sample per delivered cohort —
        # without this, a drain poll that coalesces several sub-batch
        # fires would attribute every row to the OLDEST marker's stamp
        # and overstate p99 (under-reporting the sub-batch cadence win).
        # Both deques are bounded: in modes where nothing pops them
        # (the synchronous spill+top-n drain), old entries fall off —
        # lost samples, never lost rows.
        self._fire_stamps: collections.deque = collections.deque(
            maxlen=4096)
        self._delivered_stamps: collections.deque = collections.deque(
            maxlen=512)
        # device→host copies are expensive stream ops on the measured
        # transport (~1MB/s effective for announced copies): announce
        # the ring at a TIME/FILL cadence, not per fire. The drain's
        # periodic poll reads only announced-and-landed versions, so
        # cadence bounds d2h cost without losing rows; the fill bound
        # (conservative per-fire append estimate) forces an announce
        # before the ring could wrap un-polled.
        self.emit_announce_interval_s = 0.05
        self._last_announce = 0.0
        self._rows_bound_since_announce = 0
        # 2048 rows ≈ 33KB: large against the tens of rows a steady
        # advance appends between polls, small against the ~1MB/s
        # effective cost of each announced device→host ring copy
        # (overflow is detected, loud, and names this knob)
        self.EMIT_RING_ROWS = 2048
        # bounded in-flight dispatch (credit-based flow control
        # analogue): ingest blocks on the oldest outstanding step once
        # this many are in flight, keeping the transport queue shallow
        # so emit polls/checkpoints never wait behind a deep backlog
        self.max_inflight_steps = 3
        # True when the runtime driver applies backpressure itself by
        # calling ``throttle()`` outside its push lock (see throttle())
        self.external_throttle = False
        self._inflight = collections.deque()
        # device scalars from sharded steps, resolved lazily (see
        # _resolve_overflow) — never block the pipeline per batch
        self._overflow_markers = collections.deque()
        # state.backend='spill': keys past HBM capacity aggregate on the
        # host (exact, slower) instead of dropping with a counter; the
        # shared host pool parallelizes its per-pane merges and
        # per-window fires (PROFILE §9.3)
        # state.backend='lsm' passes an externally-built disk-tier
        # store (state/lsm.py, duck-type-compatible) via spill_store;
        # plain 'spill' builds the RAM store here
        self._spill = (spill_store if spill_store is not None
                       else HostSpillStore(
                           agg, pool=host_pool,
                           fold_chunk_records=fold_chunk_records)
                       if spill else None)
        # top-n + spill: host rows can't ride per-fire markers because
        # device rows flow through the SHARED emit ring (a coalesced
        # drain would re-rank against the wrong fires). They queue here
        # and the drain merges them atomically with its ring poll.
        self._pending_ring_extras = collections.deque()
        # fused-lane pending upload (header space + u32 pairs), applied
        # by the next advance's single fused dispatch (see
        # fused_step_kernel) or flushed by _flush_stash
        self._stash_u32: Optional[np.ndarray] = None
        # device-chained generator source (see devgen_step_kernel):
        # spec, the pending batch index, and in-flight per-step stats
        # awaiting reconciliation
        self._devgen_spec = None
        self._stash_devgen: Optional[Tuple[int, int, int, bool]] = None
        self._devstats_pending: collections.deque = collections.deque()
        # RLock: the spill+top-n sync path holds it across
        # _fire_ends → drain_ring, and _fire_ends' announce block
        # takes it again (ingest vs drain-thread deque race)
        self._ring_lock = threading.RLock()
        self.plan = WindowPlan.plan(
            assigner,
            allowed_lateness_ms=allowed_lateness_ms,
            max_out_of_orderness_ms=max_out_of_orderness_ms,
        )
        if mesh_plan is not None:
            slots_per_shard = mesh_plan.slots_per_shard
            if shard_range is None:
                # single-host mesh: the directory covers every shard;
                # devices own contiguous row blocks of it
                num_shards = mesh_plan.num_shards
            elif shard_range[1] - shard_range[0] != mesh_plan.num_shards:
                # cross-host: the LOCAL mesh spans exactly this
                # process's shard range; the directory keeps the GLOBAL
                # shard space so misrouted keys are detected (-1), and
                # its LOCAL slot ids line up with the mesh row blocks
                raise ValueError(
                    f"local mesh covers {mesh_plan.num_shards} shards "
                    f"but this process's range {shard_range} spans "
                    f"{shard_range[1] - shard_range[0]}")
        self.directory = KeyDirectory(num_shards, slots_per_shard, shard_range)
        per_block_slots = (
            mesh_plan.slots_per_device if mesh_plan else self.directory.local_slots)
        self.layout = PaneStateLayout(
            slots=per_block_slots,
            ring=self.plan.ring,
            sum_width=agg.sum_width,
            max_width=agg.max_width,
            min_width=agg.min_width,
        )
        self.watermark = LONG_MIN
        self._cleared_below = self.plan.first_dead_pane(LONG_MIN)  # panes < this are dead
        self._fired_below_end: Optional[int] = None  # highest end pane fired
        self._refire: set[int] = set()
        self._min_pane_seen: Optional[int] = None
        self._max_pane_seen: Optional[int] = None
        self.late_records: int = 0
        self.exchange_overflow: int = 0
        # bumped on every mutation; checkpointing reuses the previous
        # blob when unchanged (incremental, RocksDB shared-SST analogue)
        self.state_version: int = 0
        # records dropped because the key directory shard was FULL —
        # always accounted, surfaced in metrics/JobResult (never silent)
        self.records_dropped_full: int = 0
        # per-phase wall-time accumulators (seconds) — the profile the
        # perf work is steered by (PROFILE.md); a few perf_counter calls
        # per 100k-record batch, so always on
        self.prof: Dict[str, float] = collections.defaultdict(float)

        if mesh_plan is None:
            self.state = init_state(self.layout)
            self._build_local_kernels()
        else:
            self.state = self._init_sharded_state()
            self._build_sharded_kernels()

    # -- kernel construction --------------------------------------------
    def _build_local_kernels(self) -> None:
        # module-level jits (statics in the cache key) so operators with
        # equal configuration — across jobs in one process — share one
        # compiled kernel instead of recompiling per instance
        self._apply = functools.partial(
            _JIT_APPLY,
            agg=self.agg,
            ring=self.plan.ring,
            dump_row=self.layout.slots,
        )
        # 3-byte/record upload path: eligible while the slot id fits
        # uint16 (dump row included; 0xFFFF reserved for invalid) and the
        # ring column fits uint8. Re-checked here after every ring growth.
        self._split_upload = (
            self.layout.rows <= INVALID_SLOT_U16 and self.plan.ring <= 256)
        self._apply_split = functools.partial(
            _JIT_APPLY_SPLIT, agg=self.agg, dump_row=self.layout.slots)
        # host pre-aggregation path: eligible when every accumulator
        # lane is a host-combinable sum (LaneAggregate.sum_fields) and
        # the (slot, ring column) pair domain keeps the host bincount
        # cheap. The per-batch choice (pairs vs records bytes) is
        # dynamic — see _preagg_dispatch.
        self._preagg_lanes = None
        self._preagg_ws = None  # lazy; domain changes on ring growth
        if (self.agg.max_width == 0 and self.agg.min_width == 0
                and self.agg.sum_fields is not None
                and len(self.agg.sum_fields) == self.agg.sum_width
                and self.layout.slots * self.plan.ring <= (1 << 23)):
            self._preagg_lanes = self.agg.sum_fields
        self._preagg_u16 = functools.partial(
            _JIT_PREAGG_U16, ring=self.plan.ring, dump_row=self.layout.slots)
        self._preagg_u32 = functools.partial(
            _JIT_PREAGG_U32, ring=self.plan.ring, dump_row=self.layout.slots)
        self._preagg_i32 = functools.partial(
            _JIT_PREAGG_I32, sum_width=self.agg.sum_width,
            ring=self.plan.ring, dump_row=self.layout.slots)
        self._fire_pack = functools.partial(
            _JIT_FIRE_PACK,
            agg=self.agg,
            panes_per_window=self.plan.panes_per_window,
            ring=self.plan.ring,
            packed2=self._fire_packed2(),
        )
        if self._topn is not None:
            by, n = self._topn
            self._ring_topn = functools.partial(
                _JIT_RING_TOPN,
                agg=self.agg,
                panes_per_window=self.plan.panes_per_window,
                ring=self.plan.ring,
                by=by,
                topn=n,
            )
            # one-dispatch-per-batch path (apply + fire + clear fused;
            # see fused_step_kernel) — ring must fit the 64-bit clear
            # word in the header
            self._fused_step = (functools.partial(
                _JIT_FUSED_STEP,
                agg=self.agg,
                panes_per_window=self.plan.panes_per_window,
                ring=self.plan.ring,
                by=by,
                topn=n,
                dump_row=self.layout.slots,
                fire_gate=self.fire_gate,
            ) if self.plan.ring <= 64 else None)
        else:
            self._fused_step = None
        self._clear = _JIT_CLEAR

    def _fire_pad_bucket(self, n_ends: int) -> int:
        """Static width of a fused dispatch's fire subgraph: the pow2
        bucket ≥ the sub-batch's real end count — at most
        log2(MIN_FIRE_PAD)+1 compiled buckets, shared process-wide
        through the module-level jit cache, and a steady cadence hits
        one or two of them. The fire cost (fire_kernel's rows×W
        reductions, the rows×W selection argsort, the W-way top_k)
        scales with the bucket, so K sub-batch dispatches of ~W/K real
        ends each cost ≈ one W-wide fire instead of K full-pad fires —
        the other half of the §8.6 tax next to the zero-end cond skip.
        Gating off keeps the full MIN_FIRE_PAD width (the exact
        pre-gate program, the A/B axis)."""
        if not self.fire_gate:
            return MIN_FIRE_PAD
        return min(MIN_FIRE_PAD, _next_pow2(max(n_ends, 1)))

    def _topn_cap(self, w: int) -> int:
        """Winner-buffer capacity: n rows per window plus generous tie
        headroom (ties beyond this raise at decode). Deliberately
        INDEPENDENT of the chunk's window count so every top-n fire
        buffer of this operator has one shape — the drain thread's
        stack-and-fetch then compiles exactly once."""
        n = self._topn[1]
        return _next_pow2(MAX_FIRE_CHUNK * max(64, 8 * n))

    def _fire_cap(self, w: int) -> int:
        """Static compaction capacity for a W-window fire buffer: fired
        rows per window never exceed registered keys (only used slots
        with data fire) nor the per-block slot count, so the pow2 bucket
        of that bound can never truncate. Buckets grow with key count →
        a handful of retraces over a job's life."""
        per_block = self.layout.slots
        nk = max(1, self.directory.num_keys())
        return _next_pow2(min(nk, per_block) * w)

    def _init_sharded_state(self) -> PaneState:
        mp = self.mesh_plan
        total_rows = mp.n_devices * self.layout.rows
        sharding = mp.row_sharding()

        @functools.partial(jax.jit, out_shardings=sharding)
        def init():
            def lane(width, fill):
                if width == 0:
                    return None
                return jnp.full((total_rows, self.layout.ring, width),
                                fill, jnp.float32)

            return PaneState(
                sums=lane(self.layout.sum_width, 0.0),
                maxs=lane(self.layout.max_width, -jnp.inf),
                mins=lane(self.layout.min_width, jnp.inf),
                counts=jnp.zeros((total_rows, self.layout.ring), jnp.int32),
            )

        return init()

    def _build_sharded_kernels(self) -> None:
        """The full distributed hot path: per-device bucket-by-owner →
        all_to_all over the mesh (keyBy repartition on ICI) → local pane
        scatter. Fire/clear are embarrassingly parallel over row blocks.
        """
        from flink_tpu.exchange.spi import get_shuffle

        keyby_exchange = get_shuffle(self.exchange_impl)
        mp = self.mesh_plan
        agg = self.agg
        plan = self.plan
        layout = self.layout
        spd = mp.slots_per_device
        n_dev = mp.n_devices

        ring_len = plan.ring

        def apply_shard(state, packed, data):
            # packed = global_slot * ring + ring_ix (see apply_kernel);
            # route by owner device, then rebase to the local slot block
            cap = self.exchange_capacity or packed.shape[0]
            valid = packed >= 0
            p = jnp.where(valid, packed, 0)
            slot = p // ring_len
            dest = jnp.where(valid, slot // spd, 0).astype(jnp.int32)
            payload = {"__sp__": packed, **data}
            recv, rvalid, overflow = keyby_exchange(
                dest, valid, payload, n_devices=n_dev, capacity=cap)
            my = lax.axis_index(AXIS)
            rp = recv["__sp__"]
            rvalid = rvalid & (rp >= 0)
            rq = jnp.where(rvalid, rp, 0)
            local_packed = jnp.where(
                rvalid,
                (rq // ring_len - my * spd) * ring_len + rq % ring_len,
                -1)
            new_state = apply_kernel(
                state, local_packed,
                {k: v for k, v in recv.items() if not k.startswith("__")},
                agg=agg, ring=ring_len, dump_row=layout.slots)
            return new_state, lax.psum(jnp.sum(overflow), AXIS)

        rows_local = layout.rows

        state_spec = jax.tree_util.tree_map(lambda _: P(AXIS), self.state)
        batch_spec = P(AXIS)
        rep = P()

        self._apply_sharded = jax.jit(
            shard_map(
                apply_shard, mesh=mp.mesh,
                in_specs=(state_spec, batch_spec, batch_spec),
                out_specs=(state_spec, rep),
            ),
            donate_argnums=(0,),
        )

        def apply_shard_split(state, sc, data):
            # 3-byte upload (see apply_kernel_split): decode + recombine
            # to the packed form on device — the host link gets the byte
            # savings; the ICI exchange keeps its existing layout
            slot, col = split_decode(sc)
            packed = jnp.where(
                slot == INVALID_SLOT_U16,
                jnp.int32(-1),
                slot.astype(jnp.int32) * ring_len + col.astype(jnp.int32))
            return apply_shard(state, packed, data)

        self._apply_sharded_split = jax.jit(
            shard_map(
                apply_shard_split, mesh=mp.mesh,
                in_specs=(state_spec, batch_spec, batch_spec),
                out_specs=(state_spec, rep),
            ),
            donate_argnums=(0,),
        )
        # global slot ids must fit uint16 with 0xFFFF reserved
        self._split_upload = n_dev * spd < INVALID_SLOT_U16 and ring_len <= 256

        # compaction capacity is a static shape → one compiled shard_map
        # per pow2 bucket (cached; bucket grows with registered keys)
        fire_cache: Dict[int, Any] = {}

        def fire_pack_sharded(state, params, used_mask, out_cap: int):
            fn = fire_cache.get(out_cap)
            if fn is None:
                def fire_shard(state, params, used_mask):
                    packed = fire_pack_kernel(
                        state, params, used_mask,
                        agg=agg, panes_per_window=plan.panes_per_window,
                        ring=plan.ring, out_cap=out_cap)
                    # globalize row ids (each device block carries its own
                    # rows); column 0 of body rows is the slot row, head
                    # row 0 holds n
                    my = lax.axis_index(AXIS).astype(jnp.int32)
                    offset = jnp.zeros_like(packed[:, 0]).at[1:].set(
                        my * rows_local)
                    return packed.at[:, 0].add(offset)

                fn = jax.jit(
                    shard_map(
                        fire_shard, mesh=mp.mesh,
                        in_specs=(state_spec, rep, P(AXIS)),
                        out_specs=P(AXIS),
                    )
                )
                fire_cache[out_cap] = fn
            return fn(state, params, used_mask)

        self._fire_pack = fire_pack_sharded

        if self._topn is not None:
            by, topn = self._topn
            topn_cache: Dict[int, Any] = {}

            def ring_topn_sharded(state, emit_ring, params, used_mask,
                                  sel_cap: int):
                fn = topn_cache.get(sel_cap)
                if fn is None:
                    def topn_shard(state, emit_ring, params, used_mask):
                        lo, hi, anchor, end_panes, w_valid = (
                            _unpack_fire_params(params))
                        # Global per-window threshold: each device ranks
                        # its local rows, the top-k candidates ride one
                        # tiny all_gather over ICI, every device selects
                        # its local rows against the GLOBAL n-th value
                        # (distributed RANK() <= n), and appends winners
                        # to ITS OWN block of the emit ring.
                        sums, maxs, mins, counts = fire_kernel(
                            state, end_panes, w_valid, lo, hi,
                            panes_per_window=plan.panes_per_window,
                            ring=plan.ring)
                        rows = counts.shape[0]
                        nz = ((counts > 0) & used_mask[:, None]
                              & w_valid[None, :])
                        res = agg.finalize(sums, maxs, mins, counts)
                        v = jnp.where(nz, res[by].astype(jnp.float32),
                                      -jnp.inf)
                        k = min(topn, rows)
                        local_top = lax.top_k(v.T, k)[0]           # (W, k)
                        all_top = lax.all_gather(
                            local_top, AXIS, axis=1, tiled=True)   # (W, n_dev*k)
                        # -inf thresh (< n global candidates) selects all
                        # real rows — nz masks out non-candidates
                        thresh = lax.top_k(all_top, k)[0][:, k - 1]
                        my = lax.axis_index(AXIS).astype(jnp.int32)
                        return _topn_select_append(
                            emit_ring, sums, maxs, mins, counts, nz, v,
                            thresh, end_panes, anchor, agg=agg,
                            sel_cap=sel_cap, row_offset=my * rows_local)

                    fn = jax.jit(
                        shard_map(
                            topn_shard, mesh=mp.mesh,
                            in_specs=(state_spec, P(AXIS), rep, P(AXIS)),
                            out_specs=P(AXIS),
                        )
                    )
                    topn_cache[sel_cap] = fn
                return fn(state, emit_ring, params, used_mask)

            self._ring_topn = ring_topn_sharded
        self._clear = jax.jit(
            shard_map(
                clear_kernel, mesh=mp.mesh,
                in_specs=(state_spec, rep),
                out_specs=state_spec,
            ),
            donate_argnums=(0,),
        )

    # -- data path -------------------------------------------------------
    def process_batch(
        self,
        keys: np.ndarray,
        ts: np.ndarray,
        data: Dict[str, np.ndarray],
        valid: Optional[np.ndarray] = None,
    ) -> None:
        """Fold a batch of records in. Late-beyond-lateness rows are
        dropped (side output; ref: WindowOperator sideOutput/
        numLateRecordsDropped) and late-within-lateness rows mark their
        windows for re-firing."""
        if self.uses_processing_time:
            # the record's time axis IS the clock at ingest
            ts = np.full(len(np.asarray(ts)), self.clock.now_ms(),
                         np.int64)
        # count-only fused fast lane: ONE native scan does panes, late
        # masking, drop accounting, min/max, refire candidates, and the
        # pre-agg histogram (the numpy path below makes ~6 full-array
        # passes — real milliseconds on the single-core bench host)
        if (self.mesh_plan is None
                and self._spill is None and self._preagg_lanes == ()
                and (valid is None or bool(np.all(valid)))
                and self._process_batch_fused(keys, ts)):
            return
        self._flush_stash()
        t0 = time.perf_counter()
        self.state_version += 1
        keys = np.asarray(keys, dtype=np.int64)
        ts = np.asarray(ts, dtype=np.int64)
        valid = np.ones(len(ts), bool) if valid is None else np.asarray(valid, bool)
        panes = self.plan.pane_of(ts)

        dead = self._cleared_below
        late_mask = valid & (panes < dead)
        self.late_records += int(late_mask.sum())
        valid = valid & ~late_mask

        if valid.any():
            mn = int(panes[valid].min())
            mx = int(panes[valid].max())
            prev_min = self._min_pane_seen
            prev_max = self._max_pane_seen
            if prev_min is None or mn < prev_min:
                self._min_pane_seen = mn
            if prev_max is None or mx > prev_max:
                self._max_pane_seen = mx

            # ring capacity guard: at most one live pane per ring column.
            # When event time runs ahead of the watermark clock beyond
            # plan bounds (big microbatches, stalled watermark), GROW the
            # ring and remap live columns instead of failing — the
            # backpressure answer is more memory, not a crash. The remap
            # range must cover only panes ALREADY APPLIED to state
            # (prev_min..prev_max) — this batch's panes land after the
            # grow, and remapping their columns would alias unrelated
            # live panes' data into them.
            # the live span runs to the OPERATOR max (not just this
            # batch's): a late-but-allowed record far below the live
            # range must also trigger growth, or its column write would
            # alias a newer live pane
            live_lo = max(dead, self._min_pane_seen)
            live_hi = self._max_pane_seen
            if live_hi - live_lo >= self.plan.ring:
                self._grow_ring(live_hi - live_lo + 1, prev_min, prev_max)

        # late-but-allowed → re-fire affected, already-fired windows with
        # updated contents (ref: EventTimeTrigger.onElement fires
        # immediately for late elements within allowed lateness)
        if self._fired_below_end is not None:
            late_ok = valid & (panes < self._fired_below_end)
            if late_ok.any():
                self._refire.update(self.plan.late_refire_ends(
                    panes[late_ok], self._fired_below_end, self.watermark))

        t1 = time.perf_counter()
        self.prof["pb_host_pre"] += t1 - t0
        slots = self.directory.assign(keys)
        self.prof["pb_assign"] += time.perf_counter() - t1
        bad = valid & (slots < 0)
        if bad.any():
            full = bad & (slots == KeyDirectory.FULL)
            if self._spill is not None and full.any():
                # shard full: the key aggregates on the host instead —
                # exact results at host speed (see state/spill.py)
                sub = {k: data[k][full] for k in
                       (self.agg.fields if self.agg.fields is not None
                        else data)}
                self._spill.absorb(keys[full], panes[full], sub)
                bad = bad & ~full
            # remaining negatives: shard-full without a spill store, or
            # misrouted (-1: key outside this operator's shard_range —
            # a routing error the spill store must NOT absorb, or the
            # key would aggregate on two workers at once). Default
            # policy FAILS the job; state.allow-drops=true drops with
            # accounting (see account_full_drop).
            if bad.any():
                account_full_drop(self, int(bad.sum()))
            valid = valid & ~bad & ~full
        t2 = time.perf_counter()
        if self.mesh_plan is None and self._preagg_dispatch(
                slots, panes, valid, data):
            self.prof["pb_preagg"] += time.perf_counter() - t2
            self._note_dispatch(self.state.counts[0, 0])
            if not self.external_throttle:
                self.throttle()
            return
        from flink_tpu.records import device_cast
        # upload ONLY the lanes the aggregate reads: the host→device link
        # (not the MXU) is the throughput ceiling on a remote-attached
        # chip, and e.g. Q5's count() needs no record fields at all
        if self.agg.fields is not None:
            data = {k: data[k] for k in self.agg.fields}
        data = {k: device_cast(v) for k, v in data.items()}
        # pack (slot, ring column) into one narrow array — the only
        # per-record value the device scatter needs (see apply_kernel)
        ring = self.plan.ring
        local_split = self.mesh_plan is None and self._split_upload
        if not local_split:
            packed = slots * ring + panes % ring
            packed[~valid] = -1
            # dtype bound uses GLOBAL rows: in mesh mode slots are global
            # (apply_shard routes by slot // spd), so the max packed value
            # is n_devices × the local-block bound
            n_blocks = self.mesh_plan.n_devices if self.mesh_plan else 1
            dt = np.int32 if (n_blocks * self.layout.rows + 1) * ring < 2**31 else np.int64
            packed = packed.astype(dt, copy=False)
        t3 = time.perf_counter()
        self.prof["pb_pack"] += t3 - t2
        if self.mesh_plan is None:
            if local_split:
                sc = split_encode(slots, (panes % ring).astype(np.uint8), valid)
                self.state = self._apply_split(
                    self.state, jnp.asarray(sc),
                    {k: jnp.asarray(v) for k, v in data.items()})
            else:
                self.state = self._apply(
                    self.state, jnp.asarray(packed),
                    {k: jnp.asarray(v) for k, v in data.items()})
        else:
            n_dev = self.mesh_plan.n_devices
            ov_total = None
            for pk, dt_chunk, target in self._split_for_exchange(
                    packed, data, n_dev):
                # the chunk length was pow2-bucketed + device-aligned by
                # the splitter (its capacity check ran against THIS
                # padded layout); pad to it so the device-side arrival
                # split sees exactly the blocks the check saw — and so
                # data-dependent split sizes don't compile a fresh
                # shard_map program per novel shape
                pad = target - len(pk)
                if pad:
                    pk = np.concatenate([pk, np.full(pad, -1, dt)])
                    dt_chunk = {
                        k: np.concatenate(
                            [v, np.zeros((pad,) + v.shape[1:], v.dtype)])
                        for k, v in dt_chunk.items()}
                if self._split_upload:
                    pv = pk >= 0
                    sc = split_encode(
                        np.where(pv, pk // ring, 0),
                        np.where(pv, pk % ring, 0).astype(np.uint8), pv)
                    self.state, overflow = self._apply_sharded_split(
                        self.state, jnp.asarray(sc),
                        {k: jnp.asarray(v) for k, v in dt_chunk.items()})
                else:
                    self.state, overflow = self._apply_sharded(
                        self.state, jnp.asarray(pk),
                        {k: jnp.asarray(v) for k, v in dt_chunk.items()})
                # LAZY overflow accounting: int(overflow) would block the
                # pipeline on every step. One device-side sum per PUSH
                # (not per chunk) so the marker deque stays 1:1 with
                # _inflight and throttle() never touches an in-flight
                # chunk's scalar. The host-side split makes overflow
                # structurally impossible — the counter is the backstop.
                ov_total = overflow if ov_total is None else ov_total + overflow
            if ov_total is not None:
                self._overflow_markers.append(ov_total)
        t4 = time.perf_counter()
        self.prof["pb_dispatch"] += t4 - t3
        # inflight marker: a tiny scalar DERIVED from the new state — the
        # state buffers themselves are donated to the next step, so
        # holding them would read deleted buffers
        self._note_dispatch(self.state.counts[0, 0])
        if not self.external_throttle:
            self.throttle()

    def _process_batch_fused(self, keys: np.ndarray, ts: np.ndarray) -> bool:
        """Count-only ingest via codec.cc ingest_fused_scan: ONE C pass
        does the key→slot directory probe AND the pane/late/refire/
        histogram scan (the separate assign pass wrote+reread an 8 MB
        slots array per 2^20 batch — PROFILE.md §7.4 lever a), and the
        finalize emits the packed u32 upload buffer straight from C.
        Returns False (no pane state touched; at most new keys
        registered in the directory, which assign would do anyway) when
        the native lib is missing, the batch looks high-cardinality, or
        the refire span is degenerate — the caller then runs the
        general path."""
        from flink_tpu.native_codec import (
            NativeHashTable, PreaggWorkspace,
            ingest_fused_finalize_pairs_native,
            ingest_fused_finalize_u32_native, ingest_fused_scan_native)
        if not isinstance(self.directory._table, NativeHashTable):
            return False
        keys = np.asarray(keys, dtype=np.int64)
        ts = np.asarray(ts, dtype=np.int64)
        n = len(ts)
        ring = self.plan.ring
        nk = self.directory.num_keys()
        cap = _next_pow2(max(min(n, max(nk, 1) * ring), 256))
        if 4 * cap > 2 * n or cap > (1 << 21):
            return False
        dead = self._cleared_below
        refire_below = (self._fired_below_end
                        if self._fired_below_end is not None
                        else np.iinfo(np.int64).min)
        bits = 0
        if refire_below > dead:
            span = refire_below - dead
            if span > (1 << 20):
                return False  # degenerate lateness span: general path
            bits = int(span)
        prev_min, prev_max = self._min_pane_seen, self._max_pane_seen
        t_scan = time.perf_counter()
        for _attempt in (0, 1):
            domain = self.layout.slots * self.plan.ring
            if (self._preagg_ws is None or self._preagg_ws.domain != domain
                    or self._preagg_ws.nlanes != 0):
                self._preagg_ws = PreaggWorkspace(domain, 0)
            scan = ingest_fused_scan_native(
                keys, ts, self.directory._table, self.plan.pane_ms,
                self.plan.offset_ms, self.plan.ring, self._preagg_ws,
                cap, dead, refire_below, bits, miss_cap=n)
            if scan is None:
                return False
            res, miss_ix = scan
            if len(miss_ix):
                # new keys this batch: allocate + insert (no second
                # lookup — the probe already proved absence), then
                # continue the SAME scan over just the missed records
                t1 = time.perf_counter()
                self.directory.register_misses(keys[miss_ix])
                self.prof["pb_assign"] += time.perf_counter() - t1
                scan = ingest_fused_scan_native(
                    keys[miss_ix], ts[miss_ix], self.directory._table,
                    self.plan.pane_ms, self.plan.offset_ms,
                    self.plan.ring, self._preagg_ws, cap, dead,
                    refire_below, bits, cont=res, miss_cap=1)
                if scan is None:
                    return False
                res, miss2 = scan
                if len(miss2):  # can't happen post-registration
                    self._preagg_ws.rezero()
                    return False
            (n_valid, n_late, n_bad, pmin, pmax, n_refire, _nmiss,
             cmax) = (int(x) for x in res.stats)
            if n_valid == 0:
                break
            if self._min_pane_seen is None or pmin < self._min_pane_seen:
                self._min_pane_seen = pmin
            if self._max_pane_seen is None or pmax > self._max_pane_seen:
                self._max_pane_seen = pmax
            live_lo = max(dead, self._min_pane_seen)
            live_hi = self._max_pane_seen
            if live_hi - live_lo >= self.plan.ring and _attempt == 0:
                # ring too small for the live span: grow (remapping only
                # panes applied BEFORE this batch) and redo the scan —
                # its histogram columns were taken mod the old ring
                self._preagg_ws.rezero()
                self._grow_ring(live_hi - live_lo + 1, prev_min, prev_max)
                continue
            break
        self.state_version += 1
        self.prof["preagg_combine"] += time.perf_counter() - t_scan
        self.late_records += n_late
        if n_bad:
            account_full_drop(self, n_bad)
        if n_refire:
            late_panes = (np.flatnonzero(
                np.unpackbits(res.bitmap, bitorder="little")) + dead)
            self._refire.update(self.plan.late_refire_ends(
                late_panes, self._fired_below_end, self.watermark))
        if n_valid == 0:
            return True
        tc = time.perf_counter()
        domain = self.layout.slots * self.plan.ring
        cap = _next_pow2(max(res.npairs, 256))
        if cmax < 0xFFF and domain <= (1 << 20):
            # u32 pack emitted straight from C, with fused-step header
            # space reserved up front: the pending advance fills it and
            # dispatches apply+fire+clear as ONE program with ONE upload
            buf = ingest_fused_finalize_u32_native(
                res, self._preagg_ws, FUSED_HDR, cap)
            if self._fused_step is not None and self._stash_u32 is None:
                self._stash_u32 = buf
                self.prof["pb_preagg"] += time.perf_counter() - tc
                return True
            self.state = self._preagg_u32(
                self.state, jnp.asarray(buf[FUSED_HDR:]))
        else:
            pairs, cnts = ingest_fused_finalize_pairs_native(
                res, self._preagg_ws)
            if cmax <= 0xFFFF:
                buf = preagg_encode_u16(pairs, cnts, cap)
                self.state = self._preagg_u16(self.state, jnp.asarray(buf))
            else:
                buf = preagg_encode_i32(pairs, cnts, [], cap)
                self.state = self._preagg_i32(self.state, jnp.asarray(buf))
        self.prof["pb_preagg"] += time.perf_counter() - tc
        self._note_dispatch(self.state.counts[0, 0])
        if not self.external_throttle:
            self.throttle()
        return True

    def _flush_stash(self) -> None:
        """Dispatch a pending fused-lane pair buffer as a plain apply —
        every consumer of up-to-date state (non-fused advances, fire
        chunking, snapshots, quiesce, ring growth, the general ingest
        path) calls this first."""
        buf = self._stash_u32
        if buf is None:
            return
        self._stash_u32 = None
        self.state = self._preagg_u32(
            self.state, jnp.asarray(buf[FUSED_HDR:]))
        self._note_dispatch(self.state.counts[0, 0])

    def _preagg_dispatch(
        self,
        slots: np.ndarray,
        panes: np.ndarray,
        valid: np.ndarray,
        data: Dict[str, np.ndarray],
    ) -> bool:
        """Try the host-pre-aggregated upload: combine the batch per
        (slot, ring column) pair on the host and ship one small pair
        buffer instead of per-record ids. Dispatches and returns True
        when the pair buffer is decisively smaller than the per-record
        upload (the link is the pipeline ceiling — PROFILE.md); False
        falls through to the per-record paths unchanged."""
        lanes_f = self._preagg_lanes
        if lanes_f is None:
            return False
        nv = int(valid.sum())
        if nv == 0:
            return False
        ring = self.plan.ring
        pv = panes[valid]
        span = int(pv.max() - pv.min()) + 1
        nk = self.directory.num_keys()
        bound = min(nv, max(nk, 1) * min(span, ring))
        bpp = 6 if not lanes_f else 4 * (2 + len(lanes_f))
        cap = _next_pow2(max(bound, 256))
        # decisive-win gate vs the 3 B/record split upload; high-
        # cardinality batches keep the per-record path
        if bpp * cap > 2 * len(panes):
            return False
        tc = time.perf_counter()
        domain = self.layout.slots * ring
        native = None
        if cap <= (1 << 21):
            from flink_tpu.native_codec import (
                PreaggWorkspace, preagg_combine_native)
            if (self._preagg_ws is None
                    or self._preagg_ws.domain != domain
                    or self._preagg_ws.nlanes != len(lanes_f)):
                self._preagg_ws = PreaggWorkspace(domain, len(lanes_f))
            native = preagg_combine_native(
                slots, panes, valid, [data[f] for f in lanes_f],
                ring, self._preagg_ws, cap)
        if native is not None:
            pairs, cnts, lanes = native
        else:
            pairs, cnts, lanes = preagg_combine(
                slots, panes % ring, valid, data, lanes_f,
                ring=ring, domain=domain)
        te = time.perf_counter()
        self.prof["preagg_combine"] += te - tc
        cap = _next_pow2(max(len(pairs), 256))
        cmax = 0 if len(cnts) == 0 else int(cnts.max())
        if not lanes and cmax < 0xFFF and domain <= (1 << 20):
            # tightest: one u32 per pair (pair<<12 | count)
            buf = np.full(cap, -1, np.int32)
            buf[:len(pairs)] = (pairs.astype(np.int64) << 12
                                | cnts.astype(np.int64)).astype(np.uint32
                                                                ).view(np.int32)
            th = time.perf_counter()
            dbuf = jnp.asarray(buf)
            td = time.perf_counter()
            self.state = self._preagg_u32(self.state, dbuf)
        elif not lanes and cmax <= 0xFFFF:
            buf = preagg_encode_u16(pairs, cnts, cap)
            th = time.perf_counter()
            dbuf = jnp.asarray(buf)
            td = time.perf_counter()
            self.state = self._preagg_u16(self.state, dbuf)
        else:
            buf = preagg_encode_i32(pairs, cnts, lanes, cap)
            th = time.perf_counter()
            dbuf = jnp.asarray(buf)
            td = time.perf_counter()
            self.state = self._preagg_i32(self.state, dbuf)
        tz = time.perf_counter()
        self.prof["preagg_encode"] += th - te
        self.prof["preagg_h2d"] += td - th
        self.prof["preagg_disp"] += tz - td
        return True

    def hbm_bytes(self) -> int:
        """Static device-state footprint PER DEVICE: pane tensors +
        emit ring. HBM is a per-chip resource — state shards one layout
        block per device, so widening the mesh leaves the per-chip
        footprint constant and the memory.hbm-budget check must not
        scale with fleet size."""
        state = self.layout.bytes()
        ring = 0
        if self._topn is not None:
            cols = 3 + len(self._pack_fields())
            ring = (self.EMIT_RING_ROWS + 2) * cols * 4
        return state + ring

    def _note_dispatch(self, marker, token=None, head=None) -> None:
        """Record one dispatched device step on the in-flight credit
        deque. ``marker``: a non-donated output of the step (the legacy
        is_ready probe target). ``token``: a tiny ANNOUNCED
        (copy_to_host_async) output of the same step — piggybacked
        readiness retires the step by CONSUMING its in-flight copy
        instead of probing; ``head=(i_total, i_trunc)`` names the emit-
        ring header words the token carries (coalesced readback).

        THE announce happens here, once, for every token (re-announcing
        an already-announced array is a no-op, so callers whose token
        was announced for other reasons — the devgen stats copy under
        need_stats — never double-pay): a token that skipped its
        announce would silently turn the throttle's consume into the
        unannounced blocking round trip piggyback exists to remove.
        Token-less callers (the preagg/apply/stash ingest dispatches)
        under piggyback readiness announce their MARKER instead — it is
        already a tiny derived scalar (``state.counts[0, 0]``), so the
        throttle's wait stays a transfer consume on EVERY dispatch
        plane, not just the fused/devgen advances. Probe mode announces
        nothing (zero per-step d2h, the documented trade)."""
        seq = 0
        if token is None and self.readiness == "piggyback":
            token = marker  # consume-only: carries no ring-head words
        if token is not None:
            if hasattr(token, "copy_to_host_async"):
                token.copy_to_host_async()
            self._token_seq += 1
            seq = self._token_seq
        self._inflight.append((marker, token, head, seq))

    def _retire_step(self) -> None:
        """Retire the oldest in-flight step: consume its announced
        readiness token when it has one (a wait on an in-flight
        transfer, not an extra control round trip), else fall back to
        the is_ready spin on the marker."""
        marker, token, head, seq = self._inflight.popleft()
        if token is not None:
            arr = np.asarray(token)  # blocks on the announced copy only
            if head is not None:
                self._note_ring_head(arr, head, seq)
        else:
            ready_wait(marker)

    @staticmethod
    def _raise_truncation(truncated: int) -> None:
        """The ONE top-n winner-buffer overflow error — raised from the
        ring fetch (drain_ring) and from a landed readiness token's
        head words, which detect it without a fetch."""
        raise RuntimeError(
            f"top-n winner-buffer truncation: {truncated} selected "
            "rows exceeded the per-fire selection capacity (tie "
            "explosion at the n-th value); raise n or aggregate "
            "first")

    def _note_ring_head(self, arr: np.ndarray, head, seq: int) -> None:
        """Fold a landed token's emit-ring head words into host
        knowledge: loud truncation detection without a ring fetch, and
        the drain-skip fact (see drain_ring) — the head is trusted only
        once the token postdates every row-carrying fire."""
        total = int(arr[head[0]])
        truncated = int(arr[head[1]])
        if truncated > 0:
            self._raise_truncation(truncated)
        with self._ring_lock:
            if seq >= self._rowfire_token_seq and seq > self._ring_head_seq:
                self._ring_head_seq = seq
                self._ring_head_known = True
                self._ring_head_total = total

    def throttle(self) -> None:
        """Apply ingest backpressure: block on the oldest outstanding
        step once more than ``max_inflight_steps`` are in flight. The
        driver sets ``external_throttle`` and calls this OUTSIDE its
        push lock — the block is where transfer-bound pipelines spend
        most of their time, and holding the lock through it would stall
        the drain thread's deliveries behind it (emit latency)."""
        t0 = time.perf_counter()
        while len(self._inflight) > self.max_inflight_steps:
            self._retire_step()
        # overflow markers older than the steps just retired are ready
        # (int() is a cheap host read); draining to the same bound keeps
        # the deque finite in jobs that never checkpoint
        self._resolve_overflow(bound=self.max_inflight_steps)
        self.prof["pb_throttle_wait"] += time.perf_counter() - t0

    def quiesce(self) -> None:
        """Block until every dispatched step has completed. The driver
        calls this before the FINAL watermark advance so the flush fires
        dispatch onto an idle device — their emit latency then measures
        fire+fetch, not the whole tail of the ingest pipeline."""
        self._flush_devgen()
        if self._devstats_pending:
            self._reconcile_devstats()
        self._flush_stash()
        while self._inflight:
            self._retire_step()
        ready_wait(self.state.counts)
        self._resolve_overflow()

    def _resolve_overflow(self, bound: int = 0) -> None:
        """Materialize pending exchange-overflow markers (beyond
        ``bound``) into the counter. With the host-side batch split, any
        non-zero value is a routing bug — fail loudly, not under-count."""
        while len(self._overflow_markers) > bound:
            self.exchange_overflow += int(self._overflow_markers.popleft())
        if self.exchange_overflow:
            raise RuntimeError(
                f"exchange overflow: {self.exchange_overflow} records "
                "dropped by the keyBy all_to_all despite the host-side "
                "split — per-destination routing bug")

    @staticmethod
    def _pow2_target(b: int, n_dev: int) -> int:
        """Dispatch length for a ``b``-record chunk: next pow2, then
        aligned to the device count (one compiled program per bucket)."""
        t = max(n_dev, _next_pow2(max(b, 1)))
        return t + (-t) % n_dev

    def _split_for_exchange(
            self, packed: np.ndarray, data: Dict[str, np.ndarray],
            n_dev: int) -> List[Tuple[np.ndarray, Dict, int]]:
        """Split a batch so no (source-block, destination) bucket of the
        all_to_all exchange exceeds ``exchange_capacity`` — data loss
        becomes structurally impossible instead of counted (the
        credit-based no-loss property, ref: SURVEY §3.6; a skewed key
        routing everything to one shard simply costs more steps).

        Yields ``(chunk, data, target)`` where ``target`` is the padded
        dispatch length — the capacity check runs against the SAME
        padded block layout the device-side arrival split will use
        (block length ``target // n_dev``), so an accepted chunk cannot
        overflow after padding. Capacity None = block-sized buckets,
        which can never overflow — one chunk, no check. ``b == 1`` is
        the termination backstop: a single record occupies one bucket,
        safe for any capacity ≥ 1 (enforced at config load)."""
        cap = self.exchange_capacity
        if cap is None:
            return [(packed, data, self._pow2_target(len(packed), n_dev))]
        ring = self.plan.ring
        spd = self.mesh_plan.slots_per_device
        out: List[Tuple[np.ndarray, Dict, int]] = []
        stack = [(packed, data)]
        while stack:
            pk, dt = stack.pop()
            b = len(pk)
            if not b:
                continue
            target = self._pow2_target(b, n_dev)
            L = target // n_dev  # arrival-split block length AT DISPATCH
            valid = pk >= 0
            dest = np.where(valid, (pk // ring) // spd, 0)
            block = np.arange(b) // L  # < n_dev since b <= target
            flat = np.where(valid, block * n_dev + dest, n_dev * n_dev)
            counts = np.bincount(flat, minlength=n_dev * n_dev + 1)
            if counts[:n_dev * n_dev].max(initial=0) <= cap or b <= 1:
                out.append((pk, dt, target))
            else:
                mid = b // 2
                stack.append((pk[mid:], {k: v[mid:] for k, v in dt.items()}))
                stack.append((pk[:mid], {k: v[:mid] for k, v in dt.items()}))
        return out

    def _grow_ring(
        self, need: int, applied_min: Optional[int], applied_max: Optional[int]
    ) -> None:
        """Resize the pane ring to hold ≥ ``need`` live panes and remap
        every live column old→new (global pane p moves from column
        p % old_ring to p % new_ring). Rare — a watermark stall or an
        oversized microbatch — and costs one gather + a kernel rebuild
        (recompile on next dispatch).

        ``applied_min``/``applied_max`` bound the panes actually written
        to state so far (the caller's pane-seen range BEFORE the batch
        that triggered the grow) — remapping beyond them would copy
        whatever live pane aliases those old ring columns into the new
        columns, duplicating data into phantom windows."""
        self._flush_stash()  # stashed pairs are encoded in OLD ring columns
        self._flush_devgen()  # pending device batch: same ring contract
        old_ring = self.plan.ring
        new_ring = _next_pow2(need + 4)
        lo = self._cleared_below
        if applied_min is not None:
            lo = max(lo, applied_min)
        hi = applied_max if applied_max is not None else lo - 1
        # column map: new column -> old column (or -1 = identity fill)
        cmap = np.full(new_ring, -1, np.int64)
        if hi >= lo:
            ps = np.arange(lo, hi + 1)
            cmap[ps % new_ring] = ps % old_ring

        src = jnp.asarray(np.maximum(cmap, 0).astype(np.int32))
        keep = jnp.asarray(cmap >= 0)
        new_state = _JIT_RING_REMAP(self.state, src, keep)
        if self.mesh_plan is not None:
            new_state = jax.device_put(new_state, self.mesh_plan.row_sharding())
        self.state = new_state
        self.plan = dataclasses.replace(self.plan, ring=new_ring)
        self.layout = dataclasses.replace(self.layout, ring=new_ring)
        if self.mesh_plan is None:
            self._build_local_kernels()
        else:
            self._build_sharded_kernels()

    # -- time path -------------------------------------------------------
    def advance_processing_time(self) -> "FiredWindows":
        """Fire windows the processing-time clock has passed (the
        batched ProcessingTimeTrigger). Driven by the runtime between
        steps; tests drive a ManualProcessingTimeService directly."""
        return self.advance_watermark(self.clock.now_ms() - 1)

    def advance_watermark(self, wm: int) -> "FiredWindows":
        """Advance event time; fire newly-complete windows plus pending
        re-fires; purge dead panes. Returns the fired-window batch
        (key, window_start, window_end, count, result fields...) as a
        lazy ``FiredWindows`` — the device work is dispatched here, the
        single device→host transfer happens on first access."""
        if wm < self.watermark or (wm == self.watermark and not self._refire):
            return self._empty()
        taw = time.perf_counter()
        # device-generated steps whose stats have landed: fold them in
        # (late accounting, refire scheduling, miss repair) BEFORE this
        # advance enumerates its fire list; never park behind in-flight
        # compute unless the backlog exceeds the repair deadline
        if self._devstats_pending:
            self._reconcile_devstats(force=False)
        self.state_version += 1
        prev = self.watermark
        self.watermark = wm

        ends = sorted(set(self.plan.enumerate_fire_ends(
            prev, wm, self._min_pane_seen, self._max_pane_seen))
            | self._refire)
        # the fired frontier must track the WATERMARK, not just enumerated
        # ends: a late-within-lateness record landing in any window the
        # watermark already passed (fired or empty-skipped) must trigger
        # an immediate late firing (ref: EventTimeTrigger.onElement FIREs
        # when window.maxTimestamp() <= currentWatermark)
        frontier = self.plan.fire_frontier(wm)
        if self._fired_below_end is None or frontier > self._fired_below_end:
            self._fired_below_end = frontier
        self._refire.clear()
        # device-generated path: the pending batch index + these fires
        # + the purge ride ONE dispatch whose only upload is the header
        if self._stash_devgen is not None:
            if self._stash_u32 is not None:
                self._flush_stash()  # miss repair stashed host pairs
            out = self._advance_fused_devgen(wm, ends)
            if out is not None:
                self.prof["aw_dispatch"] += time.perf_counter() - taw
                return out
            self._flush_devgen()  # fire list overflowed: chunked path
        # fused path: the pending ingest stash + these fires + the purge
        # ride ONE device dispatch with ONE upload
        if (self._stash_u32 is not None and self._fused_step is not None
                and self._spill is None and self.mesh_plan is None):
            out = self._advance_fused(wm, ends)
            if out is not None:
                self.prof["aw_dispatch"] += time.perf_counter() - taw
                return out
        self._flush_stash()
        # host-store keys fire on the SAME ends list (incl. refires) —
        # disjoint key sets, so rows simply ride along
        extra = (self._spill.fire(
            ends, self.plan.panes_per_window, self.plan.pane_ms,
            self.plan.offset_ms, self.plan.size_ms)
            if self._spill is not None and ends else None)
        if self._topn is not None and self._spill is not None:
            # top-n + spill: drain the ring SYNCHRONOUSLY at each fire.
            # Device rows flow through a shared ring with no per-fire
            # attribution, so letting the drain thread coalesce fires
            # would re-rank one fire's host rows against another fire's
            # device winners (and a refired window's stale rows would
            # poison the union — rank fields aren't monotone across
            # refires). One fire per drain makes the union re-rank
            # trivially exact. The cost — a blocking ring fetch per
            # advance — lands only in spill mode, which has already
            # traded peak speed for capacity.
            with self._ring_lock:
                out = self._fire_ends(ends)
                if extra is not None:
                    self._pending_ring_extras.append(extra)
            if out._ring or extra is not None:
                out = FiredWindows(data=self.drain_ring())
        else:
            out = self._fire_ends(ends)
            if extra is not None:
                out._extra = extra
                out._topn_spec = self._topn

        # purge panes no window can need anymore; only columns actually
        # written (>= min pane seen) can hold data
        new_dead = self.plan.first_dead_pane(wm)
        if new_dead > self._cleared_below:
            lo = self._cleared_below
            if self._min_pane_seen is not None:
                lo = max(lo, self._min_pane_seen)
            else:
                lo = new_dead  # nothing written yet — nothing to clear
            hi = new_dead
            if hi > lo:
                # padded i32 mask — see clear_kernel's transfer note
                mask = np.zeros(max(self.plan.ring, 64), dtype=np.int32)
                if hi - lo >= self.plan.ring:
                    mask[:self.plan.ring] = 1
                else:
                    ring_positions = np.arange(lo, hi) % self.plan.ring
                    mask[ring_positions] = 1
                self.state = self._clear(self.state, jnp.asarray(mask))
            self._cleared_below = new_dead
            if self._spill is not None:
                self._spill.purge_below(new_dead)
        self.prof["aw_dispatch"] += time.perf_counter() - taw
        return out

    def _fused_fill_header(self, wm: int, ends: List[int],
                           buf: np.ndarray) -> Optional[Tuple[List[int], int]]:
        """Fill the FUSED_HDR-word fused-step header in place: pane bounds,
        ring anchor, clear word, fire-end deltas. Returns
        (fired_ends, cleared_below_after) or None when the fire list
        overflows the fused window slots."""
        ppw = self.plan.panes_per_window
        if self._max_pane_seen is None:
            ends_f: List[int] = []
            lo = self._cleared_below
        else:
            lo = max(self._cleared_below, self._min_pane_seen)
            hi = self._max_pane_seen
            ends_f = [e for e in ends if e > lo and e - ppw <= hi]
        if len(ends_f) > MIN_FIRE_PAD:
            return None
        ring = self.plan.ring
        # purge decision (mirrors the non-fused tail): mask bits ride
        # the header's clear word
        new_dead = self.plan.first_dead_pane(wm)
        clear_word = 0
        cleared_after = self._cleared_below
        if new_dead > self._cleared_below:
            clo = self._cleared_below
            if self._min_pane_seen is not None:
                clo = max(clo, self._min_pane_seen)
            else:
                clo = new_dead
            if new_dead > clo:
                if new_dead - clo >= ring:
                    clear_word = (1 << ring) - 1
                else:
                    for p in range(clo, new_dead):
                        clear_word |= 1 << (p % ring)
            cleared_after = new_dead
        if self._ring_anchor is None:
            self._ring_anchor = lo
        hi_v = self._max_pane_seen if self._max_pane_seen is not None else lo - 1
        buf[:6] = np.array([lo, hi_v, self._ring_anchor],
                           np.int64).view(np.int32)
        cw = np.array([clear_word], np.uint64).view(np.int32)
        buf[7], buf[6] = cw[0], cw[1]
        deltas = np.full(MIN_FIRE_PAD, _DELTA_SENTINEL, np.int64)
        if ends_f:
            deltas[:len(ends_f)] = np.asarray(ends_f, np.int64) - lo
        buf[8:8 + MIN_FIRE_PAD] = deltas.astype(np.int32)
        return ends_f, cleared_after

    def _advance_fused(self, wm: int, ends: List[int]) -> Optional["FiredWindows"]:
        """One-dispatch advance: apply the stashed pair upload, fire up
        to MIN_FIRE_PAD window ends, and purge dead panes in a single
        fused program (see fused_step_kernel). Returns None when the
        fire list overflows the fused window slots — the caller then
        flushes the stash and takes the chunked path."""
        buf = self._stash_u32
        hdr = self._fused_fill_header(wm, ends, buf)
        if hdr is None:
            return None
        ends_f, cleared_after = hdr
        self._stash_u32 = None
        used = self._used_mask_device()
        self.state, self._emit_ring, token = self._fused_step(
            self.state, self._ensure_ring(), jnp.asarray(buf), used,
            sel_cap=self._topn_cap(MIN_FIRE_PAD),
            fire_pad=self._fire_pad_bucket(len(ends_f)))
        # the NON-donated emit-ring output doubles as the completion
        # marker — no extra gather launch, and it survives the next
        # step's donation of the state buffers. Piggyback readiness
        # additionally registers the kernel's ring-head token
        # (_note_dispatch announces it) so the throttle's wait is a
        # consume of that in-flight copy.
        if self.readiness == "piggyback":
            self._note_dispatch(self._emit_ring, token=token, head=(0, 1))
        else:
            self._note_dispatch(self._emit_ring)
        self._cleared_below = cleared_after
        return self._ring_after_fire(len(ends_f), covered=True)

    # -- device-chained generator ingest (see devgen_step_kernel) --------

    def attach_device_source(self, spec) -> bool:
        """Chain a DeviceGeneratorSource into this operator's step
        program: batches are synthesized on device and never cross the
        link. Requires the source to declare a bounded key domain — the
        directory pre-registers it densely so slot == key is a pure
        function on device (see devgen_step_kernel). Returns False when
        this operator configuration can't host it — the driver then
        materializes batches normally."""
        from flink_tpu.native_codec import NativeHashTable

        if (self._fused_step is None or self._topn is None
                or self._preagg_lanes != () or self._spill is not None
                or self.mesh_plan is not None
                or self.uses_processing_time):
            return False
        if not isinstance(self.directory._table, NativeHashTable):
            return False  # the miss-repair path needs the C probe
        d = getattr(spec, "key_domain", None)
        if d is None or d <= 0 or d > self.layout.slots:
            return False
        if self.directory.num_keys() == 0:
            self.directory.register_dense(d)
        else:
            # restored/pre-populated directory: the dense identity must
            # already hold for the WHOLE domain — a strict prefix would
            # leave slots [num_keys, d) writable by the device kernel
            # yet unregistered and unclaimed by the allocator
            if self.directory.num_keys() < d:
                return False
            probe = np.arange(d, dtype=np.int64)
            vals, found = self.directory._table.lookup_keys(probe)
            if not (found.all() and (vals == probe).all()):
                return False
        self._devgen_spec = spec
        return True

    def process_batch_device(self, batch_index: int) -> bool:
        """Accept one device-generated batch: validate the gates,
        pre-grow the ring from the HOST-KNOWN ts bounds (exact — the
        generator contract is deterministic in the batch index), and
        stash the index for the next advance's single dispatch.
        Returns False when a gate closed; the caller falls back to host
        materialization for this batch."""
        spec = self._devgen_spec
        if spec is None or self.plan.ring > 64:
            return False
        dead = self._cleared_below
        refire_below = (self._fired_below_end
                        if self._fired_below_end is not None
                        else np.iinfo(np.int64).min)
        if (refire_below > dead
                and refire_below - dead > DEVGEN_REFIRE_BITS):
            return False
        ts_min, ts_max = spec.ts_bounds(batch_index)
        pane_ms, off = self.plan.pane_ms, self.plan.offset_ms
        pmin = (int(ts_min) - off) // pane_ms
        pmax = (int(ts_max) - off) // pane_ms
        if pmax < dead:
            return False  # whole batch past lateness: host path accounts
        # a pending batch must dispatch against the CURRENT ring layout
        # before any growth remap below
        self._flush_devgen()
        eff_min = max(pmin, dead)
        prev_min, prev_max = self._min_pane_seen, self._max_pane_seen
        new_min = eff_min if prev_min is None else min(prev_min, eff_min)
        new_max = pmax if prev_max is None else max(prev_max, pmax)
        if new_max - max(dead, new_min) >= self.plan.ring:
            self._grow_ring(new_max - max(dead, new_min) + 1,
                            prev_min, prev_max)
            if self.plan.ring > 64:
                return False  # outgrew the clear words: host path
        self.state_version += 1
        self._min_pane_seen = new_min
        self._max_pane_seen = new_max
        # stats are needed only when something in them could be
        # nonzero: an unproven key bound (misses), panes below the
        # dead frontier (late accounting), or panes below the fired
        # frontier (refire candidates) — at steady state a monotone
        # source clears all three and the round trip is skipped
        need_stats = (not getattr(spec, "keys_bounded", False)
                      or pmin < dead or pmin < refire_below)
        self._stash_devgen = (int(batch_index), int(dead),
                              int(refire_below), bool(need_stats))
        if not self.external_throttle:
            self.throttle()
        return True

    def _dispatch_devgen(self, buf: np.ndarray, batch_index: int,
                         dead: int, need_stats: bool = True,
                         fire_pad: int = MIN_FIRE_PAD) -> None:
        by, n = self._topn
        step = functools.partial(
            _JIT_DEVGEN_STEP, gen=self._devgen_spec.device_keys_ts,
            key_domain=int(self._devgen_spec.key_domain),
            agg=self.agg, panes_per_window=self.plan.panes_per_window,
            ring=self.plan.ring, by=by, topn=n,
            dump_row=self.layout.slots, pane_ms=self.plan.pane_ms,
            offset_ms=self.plan.offset_ms, fire_gate=self.fire_gate)
        used = self._used_mask_device()
        self.state, self._emit_ring, stats = step(
            self.state, self._ensure_ring(), jnp.asarray(buf), used,
            sel_cap=self._topn_cap(MIN_FIRE_PAD), fire_pad=fire_pad)
        # the stats lane rides home asynchronously and reconciles at a
        # later advance; under probe readiness, when the spec PROVES
        # the key bound and the batch's pane range rules out
        # late/refire work, the whole transfer is skipped (every
        # per-step transfer is ~tens of ms of in-situ relay service).
        # Piggyback readiness registers it as the step's token instead
        # (_note_dispatch announces it): the landed copy carries the
        # post-fire ring head in words 3/5 — one transfer serves
        # accounting, the throttle, and the ring-header poll.
        if need_stats:
            if self.readiness != "piggyback" \
                    and hasattr(stats, "copy_to_host_async"):
                stats.copy_to_host_async()
            self._devstats_pending.append((batch_index, dead, stats))
        if self.readiness == "piggyback":
            self._note_dispatch(self._emit_ring, token=stats, head=(3, 5))
        else:
            self._note_dispatch(self._emit_ring)

    def _advance_fused_devgen(self, wm: int,
                              ends: List[int]) -> Optional["FiredWindows"]:
        """One-dispatch advance over a device-generated batch:
        generate + probe + apply + fire + purge in a single program
        whose only upload is the 512-byte header."""
        buf = np.zeros(FUSED_HDR, np.int32)
        hdr = self._fused_fill_header(wm, ends, buf)
        if hdr is None:
            return None
        ends_f, cleared_after = hdr
        batch_index, dead, refire_below, need_stats = self._stash_devgen
        self._stash_devgen = None
        buf[DEVGEN_HDR_OFF:DEVGEN_HDR_OFF + 6] = np.array(
            [batch_index, dead, refire_below], np.int64).view(np.int32)
        self._dispatch_devgen(buf, batch_index, dead, need_stats,
                              fire_pad=self._fire_pad_bucket(len(ends_f)))
        self._cleared_below = cleared_after
        return self._ring_after_fire(len(ends_f), covered=True)

    def _flush_devgen(self) -> None:
        """Dispatch a pending device-generated batch as a fire-less
        step — every consumer of up-to-date state calls this (snapshots,
        quiesce, ring growth, the chunked advance path)."""
        if self._stash_devgen is None:
            return
        batch_index, dead, refire_below, need_stats = self._stash_devgen
        self._stash_devgen = None
        lo = (self._cleared_below if self._min_pane_seen is None
              else max(self._cleared_below, self._min_pane_seen))
        if self._ring_anchor is None:
            self._ring_anchor = lo
        hi_v = (self._max_pane_seen if self._max_pane_seen is not None
                else lo - 1)
        buf = np.zeros(FUSED_HDR, np.int32)
        buf[:6] = np.array([lo, hi_v, self._ring_anchor],
                           np.int64).view(np.int32)
        buf[8:8 + MIN_FIRE_PAD] = np.full(MIN_FIRE_PAD, _DELTA_SENTINEL,
                                          np.int64).astype(np.int32)
        buf[DEVGEN_HDR_OFF:DEVGEN_HDR_OFF + 6] = np.array(
            [batch_index, dead, refire_below], np.int64).view(np.int32)
        self._dispatch_devgen(buf, batch_index, dead, need_stats,
                              fire_pad=self._fire_pad_bucket(0))

    # how many un-reconciled device steps may accumulate before an
    # advance force-blocks on the oldest one's stats: at steady state
    # the copies land while later batches dispatch, so reconciliation
    # is a local read; the bound keeps miss repair well inside the
    # pane ring's lifetime
    DEVSTATS_MAX_LAG = 2

    def _reconcile_devstats(self, force: bool = True) -> None:
        """Fold landed device-step stats into host accounting: late
        drops, directory-FULL drops, refire scheduling — and repair
        MISSES by re-synthesizing the batch bit-exactly on the host,
        registering the new keys, and applying just the missed records
        through the normal ingest path (their windows, if already
        fired, re-fire with corrected contents — the panes are still
        alive because reconciliation is bounded to DEVSTATS_MAX_LAG
        advances after the dispatch, well inside the ring's lifetime).

        ``force=False`` consumes only entries whose announced copy has
        LANDED (never parks behind in-flight compute — the same rule as
        the emit-ring drain), except that entries older than
        DEVSTATS_MAX_LAG block regardless."""
        while self._devstats_pending:
            if (not force
                    and len(self._devstats_pending) <= self.DEVSTATS_MAX_LAG
                    and not self._devstats_pending[0][2].is_ready()):
                return
            batch_index, dead, stats = self._devstats_pending.popleft()
            arr = np.asarray(stats)
            n_valid, n_late, n_miss, _unused, n_refire = (
                int(x) for x in arr[:5])
            self.late_records += n_late
            if n_refire:
                rbm = arr[8:8 + DEVGEN_REFIRE_BITS]
                late_panes = np.flatnonzero(rbm) + dead
                self._refire.update(self.plan.late_refire_ends(
                    late_panes, self._fired_below_end, self.watermark))
            if n_miss:
                keys, ts = self._devgen_spec.keys_ts_host(batch_index)
                out = (keys < 0) | (keys >= self._devgen_spec.key_domain)
                vals, found = self.directory._table.lookup_keys(
                    np.ascontiguousarray(keys[out], np.int64))
                # out-of-domain keys the directory already rejected as
                # FULL stay dropped — account them loudly (the
                # default-safe policy); the rest re-apply normally and
                # register through the ordinary allocation path
                n_full = int((found & (vals < 0)).sum())
                if n_full:
                    account_full_drop(self, n_full)
                redo = ~(found & (vals < 0))
                if redo.any():
                    self.process_batch(keys[out][redo], ts[out][redo], {})

    def _ring_after_fire(self, n_ends: int,
                         covered: bool = False) -> "FiredWindows":
        """Post-fire ring bookkeeping shared by the fused and chunked
        top-n paths: version bump + cadenced announce (see
        _ring_versions). ``covered``: this fire rode a dispatch whose
        readiness token carries the POST-fire ring head (the fused/
        devgen paths) — that token (or any later one) re-validates the
        piggybacked head; a chunked fire has no token of its own, so
        only a FUTURE dispatch's token can."""
        with self._ring_lock:
            self._ring_version_no += 1
            if n_ends > 0:
                # row-carrying fire: stamp the cohort for host-visibility
                # latency attribution (see _fire_stamps above)
                self._fire_stamps.append(
                    (self._ring_version_no, time.time()))
                # rows may have been appended: the piggybacked ring head
                # goes stale until a token at/after this fire lands
                self._ring_head_known = False
                self._rowfire_token_seq = (
                    self._token_seq if covered else self._token_seq + 1)
            self._rows_bound_since_announce += max(n_ends, 0) * (
                self._topn[1] * 8)
            now = time.perf_counter()
            if (now - self._last_announce >= self.emit_announce_interval_s
                    or self._rows_bound_since_announce
                    >= self.EMIT_RING_ROWS // 2):
                self._emit_ring.copy_to_host_async()
                self._ring_versions.append(
                    (self._ring_version_no, self._emit_ring))
                self._last_announce = now
                self._rows_bound_since_announce = 0
            return FiredWindows(op=self, ring=True,
                                ring_no=self._ring_version_no)

    def _fire_ends(self, ends: List[int]) -> "FiredWindows":
        if not ends or self._max_pane_seen is None:
            return self._empty()
        # windows entirely outside the written pane range are empty — skip
        lo = max(self._cleared_below, self._min_pane_seen)
        hi = self._max_pane_seen
        ppw = self.plan.panes_per_window
        ends = [e for e in ends if e > lo and e - ppw <= hi]
        if not ends:
            return self._empty()
        # pad the window axis to a power of two (compile once per bucket
        # size, not per distinct fire count) and CHUNK large fires at
        # MAX_FIRE_CHUNK windows: a catch-up advance reuses the small
        # steady-state kernels instead of compiling a one-off giant one
        used = self._used_mask_device()
        packs = []
        step = MAX_FIRE_CHUNK_RING if self._topn is not None else MAX_FIRE_CHUNK
        for c0 in range(0, len(ends), step):
            chunk = ends[c0:c0 + step]
            W = len(chunk)
            Wp = 1
            while Wp < W:
                Wp *= 2
            if self._topn is not None and self._ring_anchor is None:
                self._ring_anchor = lo
            ends_padded = chunk + [int(_END_SENTINEL)] * (
                max(Wp, MIN_FIRE_PAD) - W)
            params = jnp.asarray(np.asarray(
                [lo, hi, self._ring_anchor or 0] + ends_padded, dtype=np.int64))
            if self._topn is not None:
                self._emit_ring = self._ring_topn(
                    self.state, self._ensure_ring(), params, used,
                    sel_cap=self._topn_cap(Wp))
            else:
                buf = self._fire_pack(
                    self.state, params, used, out_cap=self._fire_cap(Wp))
                # start the device→host copy NOW: by the time the drain
                # polls, the bytes are host-cached and np.asarray is
                # ~0.2ms instead of a ~100ms blocking link round trip
                # (measured on the remote-attached chip)
                buf.copy_to_host_async()
                packs.append((lo, buf))
        if self._topn is not None:
            return self._ring_after_fire(len(ends))
        return FiredWindows(op=self, packs=packs)

    def _fire_packed2(self) -> bool:
        """Static gate of the 2-column packed fire layout (local
        path): count-only aggregate, slot ids < 2^23, end deltas < 2^8
        (delta <= live ring span + panes_per_window). All plan facts —
        never data-dependent."""
        return (self.mesh_plan is None and not self._pack_fields()
                and self.layout.slots < (1 << 23)
                and self.plan.ring + self.plan.panes_per_window
                < (1 << 8))

    def _pack_fields(self) -> List[str]:
        """Result lanes as stored in packed buffers / the emit ring —
        the result fields MINUS 'count', which always rides the exact
        i32 column 2 (storing it twice was 25% of WordCount's egress
        bytes)."""
        return [f for f in self._result_fields() if f != "count"]

    def _result_fields(self) -> List[str]:
        """Sorted result-lane field names — the packed buffer's column
        order past [row, end_delta, count]. MUST mirror
        fire_pack_kernel's ``sorted(res)`` exactly (including a result
        field named 'count' if the aggregate emits one)."""
        if not hasattr(self, "_res_fields"):
            from flink_tpu.ops.aggregates import probe_finalize

            res = probe_finalize(self.agg)
            self._res_fields = sorted(res)
            self._res_is_int = {
                k: np.issubdtype(np.asarray(res[k]).dtype, np.integer)
                for k in res
            }
        return self._res_fields

    def _decode_packs(self, packs, bufs) -> Dict[str, np.ndarray]:
        """Host-side decode of fetched fire buffers (bitcast lanes,
        slot → key, pane → window times). Each buffer's layout is read
        from ITS OWN width — decode is lazy (drain thread), and a ring
        growth between fire dispatch and materialization can flip the
        op's packed2 gate while 2-column packs are still in flight."""
        pack_fields = self._pack_fields()
        segs = []  # (buffer_body_slice, lo)
        for (lo, _), buf in zip(packs, bufs):
            if self.mesh_plan is None:
                n = int(buf[0, 0])
                self._check_fire_cap(n, len(buf) - 1)
                segs.append((buf[1:1 + n], lo))
            else:
                blk = len(buf) // self.mesh_plan.n_devices
                for d in range(self.mesh_plan.n_devices):
                    block = buf[d * blk:(d + 1) * blk]
                    n = int(block[0, 0])
                    self._check_fire_cap(n, blk - 1)
                    segs.append((block[1:1 + n], lo))
        rows_l, ep_l, cnt_l, lane_l = [], [], [], []
        for body, lo in segs:
            if body.shape[1] == 2:   # packed2: (row << 8 | delta, count)
                rows_l.append(body[:, 0] >> 8)
                ep_l.append(lo + (body[:, 0] & 0xFF).astype(np.int64))
                cnt_l.append(body[:, 1])
                # packed2 is gated to count-only aggs: no extra lanes
            else:
                rows_l.append(body[:, 0])
                ep_l.append(lo + body[:, 1].astype(np.int64))
                cnt_l.append(body[:, 2])
                lane_l.append(body[:, 3:])
        if rows_l:
            rows = np.concatenate(rows_l)
            end_pane = np.concatenate(ep_l)
            count = np.concatenate(cnt_l)
        else:
            rows = np.zeros(0, np.int32)
            end_pane = np.zeros(0, np.int64)
            count = np.zeros(0, np.int32)
        window_end = end_pane * self.plan.pane_ms + self.plan.offset_ms
        out: Dict[str, np.ndarray] = {
            "key": self.directory.key_of_slots(self._slot_of_rows(rows)),
            "window_start": window_end - self.plan.size_ms,
            "window_end": window_end,
            "count": count,
        }
        # "count" rides an exact i32 column; the pack carries only the
        # OTHER result lanes (see fire_pack_kernel)
        if pack_fields:
            lanes = (np.concatenate(lane_l) if lane_l
                     else np.zeros((0, len(pack_fields)), np.int32))
            for i, k in enumerate(pack_fields):
                col = np.ascontiguousarray(lanes[:, i])
                out[k] = (col if self._res_is_int[k]
                          else col.view(np.float32))
        return out

    def _ensure_ring(self) -> jax.Array:
        """Lazily allocate the device emit ring: row 0 = monotone counter
        head, rows 1..cap = data, last row = scatter dump."""
        if self._emit_ring is None:
            C = 3 + len(self._pack_fields())
            shape = (self.EMIT_RING_ROWS + 2, C)
            if self.mesh_plan is not None:
                n_dev = self.mesh_plan.n_devices
                self._emit_ring = jax.device_put(
                    np.zeros((n_dev * shape[0], C), np.int32),
                    self.mesh_plan.row_sharding())
                self._ring_drained_blocks = [0] * n_dev
            else:
                self._emit_ring = jnp.zeros(shape, jnp.int32)
        return self._emit_ring

    def drain_ring(self, min_no: Optional[int] = None) -> Dict[str, np.ndarray]:
        """Fetch the emit ring ONCE and decode every row appended since
        the previous drain (the host-side poll of the device emit
        buffer). Overflow — more appends than the ring holds between
        polls — is detected from the monotone counter and raises.

        ``min_no``: the oldest ring version this drain may read (a
        barrier passes its fire's version so its rows are guaranteed
        present; None = latest). The fetch prefers the newest version
        whose announced copy already landed — see _ring_versions."""
        with self._ring_lock:
            # pop pending host-spill extras together with the ring read:
            # the appender holds the same lock across (ring dispatch,
            # extra enqueue), so the rows observed here are exactly the
            # fires whose extras we pop — per-fire attribution without
            # per-fire ring segmentation
            extras = list(self._pending_ring_extras)
            self._pending_ring_extras.clear()
            if self._emit_ring is None or self._ring_anchor is None:
                arr = None
            elif (min_no == 0 and self.mesh_plan is None
                  and self._ring_head_known
                  and self._ring_head_total == self._ring_drained):
                # coalesced readback: a landed step token that postdates
                # every row-carrying fire says the ring's appended total
                # equals what this host already drained — there is
                # provably nothing to fetch, so the opportunistic poll
                # skips the device round trip outright. Barrier drains
                # (min_no > 0 / None) always fetch. The same proof
                # covers every pending fire stamp (a stamped fire
                # postdating the trusted token would have invalidated
                # the head): their rows are already host-visible, so
                # deliver the stamps NOW — a zero-row fire cohort's
                # latency sample must not age across skipped polls.
                while self._fire_stamps:
                    self._delivered_stamps.append(
                        self._fire_stamps.popleft())
                self.prof["drain_skips"] += 1
                arr = None
            else:
                tdr = time.perf_counter()
                # fetch the newest ANNOUNCED version whose async copy
                # already landed — never park behind the in-flight
                # compute of a just-dispatched fire — among versions
                # >= min_no (a barrier's rows must be present).
                need = (self._ring_version_no if min_no is None
                        else min_no)
                acceptable = [(no, arr_) for no, arr_ in
                              self._ring_versions if no >= need]
                target = None
                no_read = None
                for no, cand in reversed(acceptable):
                    if cand.is_ready():
                        target, no_read = cand, no
                        break
                else:
                    if acceptable:
                        # oldest OK = soonest
                        no_read, target = acceptable[0]
                if target is None:
                    if min_no == 0:
                        # opportunistic poll with nothing announced yet
                        # (or announce cadence not due): fetch nothing;
                        # the next poll gets it
                        arr = None
                    else:
                        # barrier needs a version newer than any
                        # announced copy: announce the live ring now so
                        # the fetch is a landed-copy read, not an
                        # unannounced round trip
                        target = self._emit_ring
                        no_read = self._ring_version_no
                        target.copy_to_host_async()
                        self._ring_versions.append(
                            (self._ring_version_no, target))
                        self._last_announce = time.perf_counter()
                        self._rows_bound_since_announce = 0
                if target is not None:
                    ready_wait(target)
                    arr = np.asarray(target)         # ONE round trip
                    # every fire cohort at or below the fetched version
                    # just became host-visible — hand its dispatch
                    # stamp to the latency accounting
                    while (self._fire_stamps
                           and self._fire_stamps[0][0] <= no_read):
                        self._delivered_stamps.append(
                            self._fire_stamps.popleft())
                self.prof["drain_fetch"] += time.perf_counter() - tdr
                self.prof["drain_fetches"] += 1
        if arr is None:
            out = dict(self._empty())
            if extras:
                out = _drain_merge_extras(out, extras, self._topn)
            return out
        row_cap = self.EMIT_RING_ROWS
        bodies = []
        if self.mesh_plan is None:
            blocks = [(arr, 0)]
        else:
            blk = len(arr) // self.mesh_plan.n_devices
            blocks = [(arr[d * blk:(d + 1) * blk], d)
                      for d in range(self.mesh_plan.n_devices)]
        for block, d in blocks:
            drained = (self._ring_drained if self.mesh_plan is None
                       else self._ring_drained_blocks[d])
            total = int(block[0, 0])
            truncated = int(block[0, 1])
            if truncated > 0:
                self._raise_truncation(truncated)
            new = total - drained
            if new > row_cap:
                raise RuntimeError(
                    f"emit ring overflow: {new} rows appended since last "
                    f"drain > capacity {row_cap}; drain more often or "
                    "raise EMIT_RING_ROWS")
            if new > 0:
                ix = (drained + np.arange(new)) % row_cap + 1
                bodies.append(block[ix])
            if self.mesh_plan is None:
                self._ring_drained = total
            else:
                self._ring_drained_blocks[d] = total
        fields = self._pack_fields()
        if bodies:
            body = np.concatenate(bodies)
        else:
            body = np.zeros((0, 3 + len(fields)), np.int32)
        rows = body[:, 0]
        end_pane = self._ring_anchor + body[:, 1].astype(np.int64)
        window_end = end_pane * self.plan.pane_ms + self.plan.offset_ms
        out: Dict[str, np.ndarray] = {
            "key": self.directory.key_of_slots(self._slot_of_rows(rows)),
            "window_start": window_end - self.plan.size_ms,
            "window_end": window_end,
            "count": body[:, 2],
        }
        for i, k in enumerate(fields):
            col = np.ascontiguousarray(body[:, 3 + i])
            out[k] = col if self._res_is_int[k] else col.view(np.float32)
        if extras:
            out = _drain_merge_extras(out, extras, self._topn)
        return out

    def take_delivered_fire_stamps(self):
        """Pop the dispatch stamps of fire cohorts whose rows became
        host-visible since the last call (see ``_fire_stamps``). The
        driver records one emit-latency sample per cohort at delivery
        time — host-visibility-accurate even when one drain poll
        coalesces many sub-batch fires."""
        with self._ring_lock:
            out = [stamp for _, stamp in self._delivered_stamps]
            self._delivered_stamps.clear()
            return out

    def _check_fire_cap(self, n: int, cap: int) -> None:
        """A packed buffer reporting more fired rows than its capacity
        means truncation — only reachable on the top-n path when ties at
        the n-th value exceed the 8× headroom. Fail loudly rather than
        emit a silently-incomplete result set."""
        if n > cap:
            raise RuntimeError(
                f"fired-row buffer overflow: {n} rows > capacity {cap} "
                "(top-n tie explosion); raise n or aggregate first")

    def _used_mask_device(self) -> jax.Array:
        """(rows,) bool on device, marking registered-key rows; re-pushed
        only when the directory registered new keys (h2d is cheap and
        one-way; the d2h round trip is what the packed fire avoids)."""
        nk = self.directory.num_keys()
        if getattr(self, "_used_pushed", -1) != nk:
            n_rows = self.layout.rows * (
                self.mesh_plan.n_devices if self.mesh_plan else 1)
            used = np.zeros(n_rows, dtype=bool)
            used_slots = np.nonzero(self.directory.used_mask())[0]
            used[self._row_of_slots(used_slots)] = True
            if self.mesh_plan is not None:
                self._used_dev = jax.device_put(used, self.mesh_plan.row_sharding())
            else:
                self._used_dev = jnp.asarray(used)
            self._used_pushed = nk
        return self._used_dev

    def _row_of_slots(self, slots: np.ndarray) -> np.ndarray:
        """Global slot id → row in the state array (sharded state carries
        one dump row per device block)."""
        if self.mesh_plan is None:
            return slots
        return self.mesh_plan.global_slot_to_row(slots)

    def _slot_of_rows(self, rows: np.ndarray) -> np.ndarray:
        if self.mesh_plan is None:
            return rows
        return rows - rows // self.layout.rows

    def _last_data_end_ms(self) -> int:
        return self.plan.last_data_end_ms(self._max_pane_seen)

    def final_watermark(self) -> int:
        """ref role: advancing to Watermark.MAX_WATERMARK on input end,
        kept finite here — see WindowPlan.final_watermark_for."""
        return self.plan.final_watermark_for(
            self.watermark, self._max_pane_seen)

    def _empty(self) -> "FiredWindows":
        """Cached empty fired-batch (a fresh one would dispatch tiny
        device ops on every no-op watermark advance)."""
        if not hasattr(self, "_empty_cache"):
            self._empty_cache = _empty_fired(self.agg)
        return FiredWindows(data=dict(self._empty_cache))

    # -- snapshot seam (checkpoint/ uses this) ---------------------------
    @property
    def records_spilled(self) -> int:
        return self._spill.records_spilled if self._spill is not None else 0

    def snapshot_state(self) -> Dict[str, Any]:
        # the snapshot must include stashed records AND every pending
        # device-step's reconciliation (miss repair may stash pairs,
        # hence the order: flush devgen → reconcile → flush pairs)
        self._flush_devgen()
        if self._devstats_pending:
            self._reconcile_devstats()
        self._flush_stash()
        self._resolve_overflow()  # a checkpoint must not hide pending loss
        spill_snap = (self._spill.snapshot()
                      if self._spill is not None else None)
        # lsm changelog cut: sealed-run files ride the checkpoint as
        # hardlinks, not serialized state — lift their name→path map to
        # the top level where the coordinator pops it for storage's
        # op_aux plane (checkpoint/storage.py save_v2)
        aux_files = (spill_snap.pop("aux_files", None)
                     if isinstance(spill_snap, dict) else None)
        out = {
            "spill": spill_snap,
            "n_dev": self.mesh_plan.n_devices if self.mesh_plan else 1,
            "ring": self.plan.ring,
            # on-device CLONE, not a fetch: the freeze stays in-loop and
            # cheap; the checkpoint executor's materialize pass does the
            # device→host transfer off the hot path (SURVEY §6.4 async
            # snapshot part). A clone is required — later steps DONATE
            # self.state's buffers, so holding the refs would read
            # deleted buffers.
            "panes": jax.tree_util.tree_map(
                lambda x: jnp.array(x, copy=True), self.state),
            "directory": self.directory.snapshot(),
            "watermark": self.watermark,
            "cleared_below": self._cleared_below,
            "fired_below_end": self._fired_below_end,
            "min_pane_seen": self._min_pane_seen,
            "max_pane_seen": self._max_pane_seen,
            "refire": sorted(self._refire),
            "late_records": self.late_records,
            "records_dropped_full": self.records_dropped_full,
        }
        if aux_files:
            out["__aux_files__"] = aux_files
        return out

    def restore_state(self, snap: Dict[str, Any]) -> None:
        panes = snap["panes"]
        snap_ring = snap.get("ring", self.plan.ring)
        if snap_ring != self.plan.ring:
            # the snapshotted operator had auto-grown its pane ring —
            # adopt that geometry before loading the arrays
            self.plan = dataclasses.replace(self.plan, ring=snap_ring)
            self.layout = dataclasses.replace(self.layout, ring=snap_ring)
            if self.mesh_plan is None:
                self._build_local_kernels()
            else:
                self._build_sharded_kernels()
        snap_dev = snap.get("n_dev", 1)
        cur_dev = self.mesh_plan.n_devices if self.mesh_plan else 1
        if snap_dev != cur_dev:
            # RESHARD: the key-shard space is fixed (the maxParallelism
            # contract) but the device count changed — re-block the row
            # axis, dropping the old per-block dump rows and inserting
            # fresh ones (ref role: StateAssignmentOperation re-splitting
            # key-group ranges on rescale)
            panes = _reblock_panes(panes, snap_dev, cur_dev)
        state = jax.tree_util.tree_map(jnp.asarray, panes)
        if self.mesh_plan is not None:
            state = jax.device_put(state, self.mesh_plan.row_sharding())
        self.state = state
        self.directory = KeyDirectory.restore(
            self.directory.num_shards, self.directory.slots_per_shard,
            snap["directory"], (self.directory.shard_lo, self.directory.shard_hi))
        self.watermark = snap["watermark"]
        self._cleared_below = snap["cleared_below"]
        self._fired_below_end = snap["fired_below_end"]
        self._min_pane_seen = snap["min_pane_seen"]
        self._max_pane_seen = snap["max_pane_seen"]
        self._refire = set(snap["refire"])
        self.late_records = snap["late_records"]
        self.records_dropped_full = snap.get("records_dropped_full", 0)
        # pre-restore device steps are from a dead timeline (their
        # in-flight markers/tokens included — a stale token's ring head
        # must never be folded into the restored timeline's facts)
        self._stash_devgen = None
        self._devstats_pending.clear()
        self._inflight.clear()
        snap_spill = snap.get("spill")
        if self._spill is not None and snap_spill is not None:
            if isinstance(self._spill, HostSpillStore):
                if snap_spill.get("kind") == "lsm":
                    # lsm→spill flip: the delta restores (same pane
                    # form) but sealed runs hold state a RAM store has
                    # no files for — refuse rather than silently drop
                    if snap_spill.get("runs"):
                        raise ValueError(
                            "snapshot carries "
                            f"{len(snap_spill['runs'])} sealed lsm "
                            "run(s) the RAM spill store cannot adopt; "
                            "restore with state.backend='lsm'")
                    self._spill.restore(snap_spill["delta"])
                else:
                    self._spill.restore(snap_spill)
            else:
                # disk tier: accepts both the lsm form (aux maps run
                # name → checkpoint hardlink, injected by storage.load)
                # and a plain spill snapshot (spill→lsm backend flip)
                self._spill.restore(
                    snap_spill, aux_paths=snap.get("__aux_paths__"))
        elif self._spill is None and snap_spill and (
                snap_spill.get("panes") or snap_spill.get("runs")
                or (snap_spill.get("delta") or {}).get("panes")):
            # the snapshot carries live host-resident aggregates this
            # operator (state.backend='hbm') cannot hold — restoring
            # would silently lose them
            raise ValueError(
                "snapshot contains host-spill state but state.backend "
                "is 'hbm'; restore with state.backend='spill' or 'lsm'")
        self._used_pushed = -1  # directory changed: invalidate device used-mask
        # emit ring resets: everything it held was delivered before the
        # snapshot (checkpoint flushes emits first); replay re-fires
        self._emit_ring = None
        self._ring_drained = 0
        self._ring_anchor = None
        self._ring_versions.clear()
        self._fire_stamps.clear()
        self._delivered_stamps.clear()
        # piggybacked ring-head facts describe the pre-restore timeline
        self._ring_head_known = False
        self._ring_head_seq = self._token_seq
        self._rowfire_token_seq = self._token_seq + 1
        # a stash from the pre-restore attempt belongs to a replayed
        # stream position — never apply it to restored state
        self._stash_u32 = None


def _reblock_panes(panes: PaneState, old_dev: int, new_dev: int) -> PaneState:
    """Re-block state rows from old_dev device blocks to new_dev blocks.
    Each block is (slots_local + 1 dump) rows; logical slot order is
    preserved (global slot = shard * slots_per_shard, contiguous)."""

    def reblock(arr: np.ndarray, dump_fill) -> np.ndarray:
        arr = np.asarray(arr)
        rpl = arr.shape[0] // old_dev          # rows per old block
        blocks = [arr[d * rpl:(d + 1) * rpl - 1] for d in range(old_dev)]
        logical = np.concatenate(blocks)       # (total_slots, ...)
        if logical.shape[0] % new_dev != 0:
            raise ValueError(
                f"cannot reshard {logical.shape[0]} slots onto {new_dev} "
                "devices — num_shards * slots_per_shard must be divisible "
                "by the device count (the key-group contract)")
        slots_new = logical.shape[0] // new_dev
        out = []
        for d in range(new_dev):
            blk = logical[d * slots_new:(d + 1) * slots_new]
            dump = np.full((1,) + arr.shape[1:], dump_fill, dtype=arr.dtype)
            out.append(np.concatenate([blk, dump]))
        return np.concatenate(out)

    return PaneState(
        sums=None if panes.sums is None else reblock(panes.sums, 0.0),
        maxs=None if panes.maxs is None else reblock(panes.maxs, -np.inf),
        mins=None if panes.mins is None else reblock(panes.mins, np.inf),
        counts=reblock(panes.counts, 0),
    )


class FiredWindows(Mapping):
    """A fired-window batch with lazy host materialization.

    The device work (fire + select + finalize) was already dispatched
    when this object was created; only the device→host transfer is
    deferred to first access. The runtime driver drains these on a
    separate thread — the analogue of the reference handing serialized
    buffers to Netty's IO thread off the mailbox thread (ref:
    runtime/io/network/api/writer/RecordWriter.java → PipelinedSubpartition
    .notifyDataAvailable), so emission latency never blocks ingest.
    ``materialize_many`` fetches a whole backlog of fires in ONE
    device→host round trip (the transport serializes round trips, so
    one per fire is the emit-path latency floor — batch them)."""

    def __init__(self, data: Optional[Dict[str, np.ndarray]] = None,
                 fetch=None, op=None, packs=None, ring: bool = False,
                 ring_no: int = 0):
        self._data = data
        self._fetch = fetch
        self._op = op
        self._packs = packs
        self._ring = ring
        self._ring_no = ring_no
        # host-spill rows fired alongside this batch (disjoint keys);
        # merged in at materialization, reranked if a top-n is active
        self._extra: Optional[Dict[str, np.ndarray]] = None
        self._topn_spec: Optional[Tuple[str, int]] = None

    def materialize(self) -> Dict[str, np.ndarray]:
        if self._data is None:
            if self._fetch is not None:
                self._data = self._fetch()
                self._fetch = None
            elif self._ring:
                self._data = self._op.drain_ring()
                self._op = None
            else:
                bufs = jax.device_get([b for _, b in self._packs])
                self._data = self._op._decode_packs(self._packs, bufs)
                self._packs = self._op = None
        if self._extra is not None:
            self._data = _merge_spill_rows(
                self._data, self._extra, self._topn_spec)
            self._extra = None
        return self._data

    @staticmethod
    def materialize_many(fireds: List["FiredWindows"],
                         barrier: bool = False) -> None:
        """Fetch every pending buffer across ``fireds`` in as few
        device→host round trips as possible, then decode each.

        Every fire dispatch already issued ``copy_to_host_async`` on its
        buffers (see _fire_ends), so by drain time the bytes are
        host-cached and each np.asarray is a local read (~0.2ms measured
        on the remote-attached chip) instead of a blocking ~100ms link
        round trip. A buffer whose copy has not landed yet simply blocks
        on its own in-flight copy — never a second transfer."""
        # ring-mode entries: ONE ring poll per operator serves every
        # pending marker of that operator (later markers read empty —
        # the first drain already took the appended rows)
        # A periodic drain fetches whatever announced ring version has
        # already landed (min_no=0) — rows still in flight are simply
        # picked up by the next poll, so it NEVER parks behind a
        # just-dispatched fire's compute. A barrier drain (checkpoint
        # flush, end of job) pins each op's newest marker version so
        # every enqueued row is guaranteed fetched.
        need: Dict[int, int] = {}
        for f in fireds:
            if f._data is None and f._ring:
                cur = need.get(id(f._op), 0)
                need[id(f._op)] = (max(cur, f._ring_no) if barrier else 0)
        ring_ops = {}
        for f in fireds:
            if f._data is None and f._ring:
                op = f._op
                if id(op) not in ring_ops:
                    ring_ops[id(op)] = op.drain_ring(min_no=need[id(op)])
                    f._data = ring_ops[id(op)]
                else:
                    f._data = op._empty().materialize()
                f._op = None
        for f in fireds:
            if f._data is None and f._packs is not None:
                bufs = [np.asarray(ready_wait(b)) for _, b in f._packs]
                f._data = f._op._decode_packs(f._packs, bufs)
                f._packs = f._op = None

    def __getitem__(self, key: str) -> np.ndarray:
        return self.materialize()[key]

    def __iter__(self):
        return iter(self.materialize())

    def __len__(self) -> int:
        return len(self.materialize())


def _merge_spill_rows(
    dev: Dict[str, np.ndarray], extra: Dict[str, np.ndarray],
    topn: Optional[Tuple[str, int]],
) -> Dict[str, np.ndarray]:
    """Concatenate device-fired and host-spill-fired rows (pack-mode
    path — per-fire attribution is exact there, and pack mode never has
    a top-n, so this is a plain field-wise concat; the ``topn`` arg is
    accepted for symmetry and future-proofing)."""
    out = {k: np.concatenate([np.asarray(dev[k]), np.asarray(extra[k])])
           for k in dev}
    if topn is None or len(out["window_end"]) == 0:
        return out
    field, n = topn
    keep = _topn_keep(out["window_end"], np.asarray(out[field]), n)
    return {k: val[keep] for k, val in out.items()}


def _topn_keep(we: np.ndarray, v: np.ndarray, n: int,
               windows: Optional[np.ndarray] = None) -> np.ndarray:
    """Boolean keep-mask for per-window top-n with ties kept. When
    ``windows`` is given, only those windows are filtered; rows of other
    windows pass through."""
    keep = np.ones(len(we), bool)
    for w in (np.unique(we) if windows is None else windows):
        grp = np.flatnonzero(we == w)
        if len(grp) > n:
            gv = v[grp]
            thresh = np.partition(gv, len(gv) - n)[len(gv) - n]
            keep[grp[gv < thresh]] = False  # ties at thresh stay
    return keep


def _drain_merge_extras(
    dev: Dict[str, np.ndarray], extras: List[Dict[str, np.ndarray]],
    topn: Optional[Tuple[str, int]],
) -> Dict[str, np.ndarray]:
    """Merge host-spill extras into a ring-drain batch and re-rank the
    windows the extras touch.

    The device's ring rows are top-n of RESIDENT keys only; the global
    top-n is always a subset of device-winners ∪ host rows, so the
    union re-rank over a SINGLE fire is exact — and spill+top-n mode
    drains synchronously per fire (see advance_watermark), so a drain
    never mixes fires. Windows with no host rows pass through."""
    ex = {k: np.concatenate([np.asarray(e[k]) for e in extras])
          for k in extras[0]}
    comb = {k: np.concatenate([np.asarray(dev[k]), ex[k]]) for k in dev}
    if topn is None:
        return comb
    field, n = topn
    keep = _topn_keep(comb["window_end"], np.asarray(comb[field]), n,
                      windows=np.unique(ex["window_end"]))
    return {k: v[keep] for k, v in comb.items()}


def _empty_fired(agg: LaneAggregate) -> Dict[str, np.ndarray]:
    out = {
        "key": np.zeros(0, np.int64),
        "window_start": np.zeros(0, np.int64),
        "window_end": np.zeros(0, np.int64),
        "count": np.zeros(0, np.int32),
    }
    res = agg.finalize(
        jnp.zeros((0, agg.sum_width)), jnp.zeros((0, agg.max_width)),
        jnp.zeros((0, agg.min_width)), jnp.zeros((0,), jnp.int32))
    for k, v in res.items():
        out[k] = np.asarray(v)
    return out
