"""Non-keyed (global) windowed aggregation — the windowAll shape.

The reference lowers ``windowAll`` to a parallelism-1 WindowOperator:
every record funnels to ONE subtask (ref: streaming/api/datastream/
AllWindowedStream.java; DataStream.windowAll forces parallelism 1).
Round 2 mirrored that with a constant key — a single-shard hotspot on
any mesh (the exact skew the exchange exists to avoid).

TPU-first redesign: a global lane aggregate per pane is a few floats of
state, and folding a record into it is one segment-reduce — the work is
BANDWIDTH, not FLOPs. Measured on the remote-attached chip (PROFILE.md
§2), the host↔device link moves ~25-35 MB/s while host numpy
segment-reduces run at GB/s: shipping records to the MXU to compute a
running max would spend 30x longer on the wire than the host spends on
the whole reduction. So the fold runs HOST-SIDE, vectorized, per pane
(reusing the spill store's (key, pane) machinery with a constant key),
and nothing ever crosses the link. On a mesh this also deletes the
hotspot outright: there is no keyed exchange, and in a multi-host
deployment each runner pre-reduces its own arrivals — the cross-runner
combine is panes x width floats, the "per-device partial + tiny global
reduce" shape.

Fire/lateness/refire semantics mirror WindowOperator's (same WindowPlan
pane math, same fireable-ends enumeration, same late-within-lateness
re-fire rule).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.api.windowing import WindowAssigner
from flink_tpu.ops.aggregates import LaneAggregate
from flink_tpu.ops.window import FiredWindows, WindowPlan, _empty_fired
from flink_tpu.state.spill import HostSpillStore
from flink_tpu.time.watermarks import LONG_MIN


class WindowAllOperator:
    """Global tumbling/sliding window over ALL records (no key)."""

    def __init__(
        self,
        assigner: WindowAssigner,
        agg: LaneAggregate,
        *,
        allowed_lateness_ms: int = 0,
        max_out_of_orderness_ms: int = 0,
    ) -> None:
        self.agg = agg
        self.plan = WindowPlan.plan(
            assigner,
            allowed_lateness_ms=allowed_lateness_ms,
            max_out_of_orderness_ms=max_out_of_orderness_ms)
        self.store = HostSpillStore(agg)
        self.watermark = LONG_MIN
        self.late_records = 0
        self.state_version = 0
        self._refire: set[int] = set()
        self._cleared_below = self.plan.first_dead_pane(LONG_MIN)
        self._fired_below_end: Optional[int] = None
        self._min_pane_seen: Optional[int] = None
        self._max_pane_seen: Optional[int] = None
        self._empty_cache: Optional[Dict[str, np.ndarray]] = None

    # -- data plane ------------------------------------------------------

    def process_batch(
        self,
        ts: np.ndarray,
        data: Dict[str, np.ndarray],
        valid: Optional[np.ndarray] = None,
    ) -> None:
        self.state_version += 1
        ts = np.asarray(ts, dtype=np.int64)
        b = len(ts)
        valid = np.ones(b, bool) if valid is None else np.asarray(valid, bool)
        panes = self.plan.pane_of(ts)

        late = valid & (panes < self._cleared_below)
        self.late_records += int(late.sum())
        valid = valid & ~late
        if not valid.any():
            return
        mn, mx = int(panes[valid].min()), int(panes[valid].max())
        if self._min_pane_seen is None or mn < self._min_pane_seen:
            self._min_pane_seen = mn
        if self._max_pane_seen is None or mx > self._max_pane_seen:
            self._max_pane_seen = mx

        # late-but-allowed records re-fire already-fired windows with
        # updated contents (same shared rule as WindowOperator)
        if self._fired_below_end is not None:
            late_ok = valid & (panes < self._fired_below_end)
            if late_ok.any():
                self._refire.update(self.plan.late_refire_ends(
                    panes[late_ok], self._fired_below_end, self.watermark))

        sub = {k: np.asarray(data[k])[valid] for k in
               (self.agg.fields if self.agg.fields is not None else data)}
        self.store.absorb(np.zeros(int(valid.sum()), np.int64),
                          panes[valid], sub)

    # -- time plane ------------------------------------------------------

    def advance_watermark(self, wm: int) -> FiredWindows:
        if wm < self.watermark or (wm == self.watermark and not self._refire):
            return self._empty()
        self.state_version += 1
        prev = self.watermark
        self.watermark = wm
        ends = sorted(set(self.plan.enumerate_fire_ends(
            prev, wm, self._min_pane_seen, self._max_pane_seen))
            | self._refire)
        frontier = self.plan.fire_frontier(wm)
        if self._fired_below_end is None or frontier > self._fired_below_end:
            self._fired_below_end = frontier
        self._refire.clear()

        rows = self.store.fire(ends, self.plan.panes_per_window,
                               self.plan.pane_ms, self.plan.offset_ms,
                               self.plan.size_ms)
        new_dead = self.plan.first_dead_pane(wm)
        if new_dead > self._cleared_below:
            self._cleared_below = new_dead
            self.store.purge_below(new_dead)
        if rows is None:
            return self._empty()
        rows.pop("key")  # global window: no key column in the output
        return FiredWindows(data=rows)

    def final_watermark(self) -> int:
        return self.plan.final_watermark_for(
            self.watermark, self._max_pane_seen)

    def quiesce(self) -> None:
        pass

    def throttle(self) -> None:
        pass

    def _empty(self) -> FiredWindows:
        if self._empty_cache is None:
            cache = _empty_fired(self.agg)
            cache.pop("key", None)
            self._empty_cache = cache
        return FiredWindows(data=dict(self._empty_cache))

    # -- snapshot seam ----------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "kind": "window_all",
            "store": self.store.snapshot(),
            "watermark": self.watermark,
            "late_records": self.late_records,
            "refire": sorted(self._refire),
            "cleared_below": self._cleared_below,
            "fired_below_end": self._fired_below_end,
            "min_pane_seen": self._min_pane_seen,
            "max_pane_seen": self._max_pane_seen,
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.store.restore(snap["store"])
        self.watermark = snap["watermark"]
        self.late_records = snap["late_records"]
        self._refire = set(snap["refire"])
        self._cleared_below = snap["cleared_below"]
        self._fired_below_end = snap["fired_below_end"]
        self._min_pane_seen = snap["min_pane_seen"]
        self._max_pane_seen = snap["max_pane_seen"]
