"""Non-keyed (global) windowed aggregation — the windowAll shape.

The reference lowers ``windowAll`` to a parallelism-1 WindowOperator:
every record funnels to ONE subtask (ref: streaming/api/datastream/
AllWindowedStream.java; DataStream.windowAll forces parallelism 1).
Round 2 mirrored that with a constant key — a single-shard hotspot on
any mesh (the exact skew the exchange exists to avoid).

TPU-first redesign: a global lane aggregate per pane is a few floats of
state, and folding a record into it is one segment-reduce — the work is
BANDWIDTH, not FLOPs. Measured on the remote-attached chip (PROFILE.md
§2), the host↔device link moves ~25-35 MB/s while host numpy
segment-reduces run at GB/s: shipping records to the MXU to compute a
running max would spend 30x longer on the wire than the host spends on
the whole reduction. So the fold runs HOST-SIDE, vectorized, per pane
(reusing the spill store's (key, pane) machinery with a constant key),
and nothing ever crosses the link. On a mesh this also deletes the
hotspot outright: there is no keyed exchange, and in a multi-host
deployment each runner pre-reduces its own arrivals — the cross-runner
combine is panes x width floats, the "per-device partial + tiny global
reduce" shape.

Fire/lateness/refire semantics mirror WindowOperator's (same WindowPlan
pane math, same fireable-ends enumeration, same late-within-lateness
re-fire rule).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.api.windowing import WindowAssigner
from flink_tpu.ops.aggregates import LaneAggregate
from flink_tpu.ops.host_control import HostPaneControl
from flink_tpu.ops.window import FiredWindows, WindowPlan, _empty_fired
from flink_tpu.state.spill import HostSpillStore


class WindowAllOperator:
    """Global tumbling/sliding window over ALL records (no key)."""

    def __init__(
        self,
        assigner: WindowAssigner,
        agg: LaneAggregate,
        *,
        allowed_lateness_ms: int = 0,
        max_out_of_orderness_ms: int = 0,
        host_pool: Optional[Any] = None,
        fold_chunk_records: Optional[int] = None,
    ) -> None:
        self.agg = agg
        self.plan = WindowPlan.plan(
            assigner,
            allowed_lateness_ms=allowed_lateness_ms,
            max_out_of_orderness_ms=max_out_of_orderness_ms)
        # the global fold is ONE logical key, so key-sharding cannot
        # apply; scaling is the store's chunked tree fold over batch
        # slices + per-window parallel fires (PROFILE §9.2), gated on
        # the fold_chunk_records batch floor
        self.store = HostSpillStore(agg, pool=host_pool,
                                    fold_chunk_records=fold_chunk_records)
        self.ctl = HostPaneControl(self.plan)
        self.state_version = 0
        self._empty_cache: Optional[Dict[str, np.ndarray]] = None

    @property
    def watermark(self) -> int:
        return self.ctl.watermark

    @property
    def late_records(self) -> int:
        return self.ctl.late_records

    # -- data plane ------------------------------------------------------

    def process_batch(
        self,
        ts: np.ndarray,
        data: Dict[str, np.ndarray],
        valid: Optional[np.ndarray] = None,
    ) -> None:
        self.state_version += 1
        ts = np.asarray(ts, dtype=np.int64)
        b = len(ts)
        valid = np.ones(b, bool) if valid is None else np.asarray(valid, bool)
        panes, valid = self.ctl.absorb_panes(ts, valid)
        if not valid.any():
            return

        sub = {k: np.asarray(data[k])[valid] for k in
               (self.agg.fields if self.agg.fields is not None else data)}
        self.store.absorb(np.zeros(int(valid.sum()), np.int64),
                          panes[valid], sub)

    # -- time plane ------------------------------------------------------

    def advance_watermark(self, wm: int) -> FiredWindows:
        ends = self.ctl.begin_advance(wm)
        if ends is None:
            return self._empty()
        self.state_version += 1
        rows = self.store.fire(ends, self.plan.panes_per_window,
                               self.plan.pane_ms, self.plan.offset_ms,
                               self.plan.size_ms)
        new_dead = self.ctl.purge_horizon(wm)
        if new_dead is not None:
            self.store.purge_below(new_dead)
        if rows is None:
            return self._empty()
        rows.pop("key")  # global window: no key column in the output
        return FiredWindows(data=rows)

    def final_watermark(self) -> int:
        return self.ctl.final_watermark()

    def quiesce(self) -> None:
        pass

    def throttle(self) -> None:
        pass

    def _empty(self) -> FiredWindows:
        if self._empty_cache is None:
            cache = _empty_fired(self.agg)
            cache.pop("key", None)
            self._empty_cache = cache
        return FiredWindows(data=dict(self._empty_cache))

    # -- snapshot seam ----------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "kind": "window_all",
            "store": self.store.snapshot(),
            **self.ctl.snapshot(),
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.store.restore(snap["store"])
        self.ctl.restore(snap)
