"""Evicting / custom-trigger window operator — the ELEMENT-BUFFER path.

ref: streaming/runtime/operators/windowing/EvictingWindowOperator.java
+ evictors/{Evictor,CountEvictor,TimeEvictor}.java + the Trigger SPI
(triggers/Trigger.java: onElement/onEventTime returning
CONTINUE/FIRE/PURGE/FIRE_AND_PURGE).

Why a separate operator: the TPU-first pane backend aggregates
INCREMENTALLY — elements are folded into (key, pane) accumulator cells
the moment they arrive and never materialize again, which is exactly
what makes the hot path one dense scatter. Evictors and arbitrary
user triggers need the opposite contract: the window's ELEMENTS must
still exist at fire time (the reference pays the same price — its
EvictingWindowOperator switches the window state from an aggregate to
a ListState of all elements). So this operator keeps per-(key, window)
element buffers on the host and trades throughput for exact reference
semantics; jobs that need evictors or custom triggers route here, and
everything else stays on the pane kernels.

Supported: any WindowAssigner with assign_windows (tumbling/sliding),
user Trigger subclasses (on_element / on_event_time), CountEvictor /
TimeEvictor (evict BEFORE the window function, the reference default),
allowed lateness with re-firing, and a user window function applied to
the surviving elements.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.api.windowing import (
    EventTimeTrigger, TimeWindow, Trigger, TriggerResult)
from flink_tpu.time.watermarks import LONG_MIN


class Evictor:
    """ref: evictors/Evictor.java — evict_before receives the window's
    elements (ts plus field arrays, arrival-ordered) and returns the
    KEEP mask."""

    def evict_before(self, ts: np.ndarray, data: Dict[str, np.ndarray],
                     window: TimeWindow) -> np.ndarray:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class CountEvictor(Evictor):
    """Keep only the LAST ``max_count`` elements (ref: CountEvictor)."""

    max_count: int

    @classmethod
    def of(cls, n: int) -> "CountEvictor":
        return cls(n)

    def evict_before(self, ts, data, window):
        keep = np.zeros(len(ts), bool)
        keep[max(0, len(ts) - self.max_count):] = True
        return keep


@dataclasses.dataclass(frozen=True)
class TimeEvictor(Evictor):
    """Keep elements within ``keep_ms`` of the window's newest element
    (ref: TimeEvictor.of(Time))."""

    keep_ms: int

    @classmethod
    def of_ms(cls, keep_ms: int) -> "TimeEvictor":
        return cls(keep_ms)

    def evict_before(self, ts, data, window):
        if not len(ts):
            return np.zeros(0, bool)
        return ts > ts.max() - self.keep_ms


class _Buf:
    """Arrival-ordered element buffer for one (key, window)."""

    __slots__ = ("ts", "data", "fired", "trig_count")

    def __init__(self) -> None:
        self.ts: List[int] = []
        self.data: List[Dict[str, Any]] = []
        self.fired = False
        # per-window trigger counter, RESET on fire (ref: CountTrigger
        # keeps a ReducingState it clears when it fires)
        self.trig_count = 0


class EvictingWindowOperator:
    """Driver-protocol operator (process_batch / advance_watermark /
    take_fired / snapshot seam), host-looped for exact per-element
    trigger semantics."""

    def __init__(
        self,
        assigner,
        window_fn: Callable[[Dict[str, np.ndarray]], Dict[str, Any]],
        *,
        trigger: Optional[Trigger] = None,
        evictor: Optional[Evictor] = None,
        allowed_lateness_ms: int = 0,
    ) -> None:
        self.assigner = assigner
        self.window_fn = window_fn
        self.trigger = trigger or EventTimeTrigger.create()
        self.evictor = evictor
        self.lateness = allowed_lateness_ms
        self.watermark = LONG_MIN
        self.late_records = 0
        self.records_dropped_full = 0
        self.state_version = 0
        self.allow_drops = False
        # (key, TimeWindow) -> _Buf
        self._bufs: Dict[Tuple[int, TimeWindow], _Buf] = {}
        self._emitted: List[Dict[str, np.ndarray]] = []

    # -- data plane ------------------------------------------------------

    def process_batch(self, keys, ts, data: Dict[str, np.ndarray],
                      valid=None) -> None:
        self.state_version += 1
        keys = np.asarray(keys, np.int64)
        ts = np.asarray(ts, np.int64)
        valid = (np.ones(len(ts), bool) if valid is None
                 else np.asarray(valid, bool))
        fields = {k: np.asarray(v) for k, v in data.items()}
        for i in np.nonzero(valid)[0]:
            t = int(ts[i])
            k = int(keys[i])
            windows = self.assigner.assign_windows(t)
            live = [w for w in windows
                    if not (w.end - 1 + self.lateness <= self.watermark)]
            if not live:
                self.late_records += 1
                continue
            row = {f: fields[f][i] for f in fields}
            for w in live:
                buf = self._bufs.setdefault((k, w), _Buf())
                buf.ts.append(t)
                buf.data.append(row)
                buf.trig_count += 1
                r = self.trigger.on_element(t, w, buf.trig_count)
                if r in (TriggerResult.FIRE, TriggerResult.FIRE_AND_PURGE):
                    self._fire(k, w, buf,
                               purge=(r == TriggerResult.FIRE_AND_PURGE))
                # Late-within-lateness: the watermark already passed
                # w.end-1, so advance_watermark's pass over this window
                # is behind us (or the window didn't exist yet). Any
                # watermark-family trigger (EventTimeTrigger, or a
                # PurgingTrigger wrapping one) must (re-)fire NOW —
                # regardless of whether the window fired before.
                elif (self.watermark >= w.end - 1
                        and self.trigger.fires_on_watermark()):
                    rl = self.trigger.on_event_time(self.watermark, w)
                    if rl in (TriggerResult.FIRE,
                              TriggerResult.FIRE_AND_PURGE):
                        self._fire(
                            k, w, buf,
                            purge=(rl == TriggerResult.FIRE_AND_PURGE))

    def _fire(self, key: int, w: TimeWindow, buf: _Buf,
              purge: bool) -> None:
        ts = np.asarray(buf.ts, np.int64)
        data = ({f: np.asarray([r[f] for r in buf.data])
                 for f in buf.data[0]} if buf.data and buf.data[0] else {})
        if self.evictor is not None:
            keep = np.asarray(
                self.evictor.evict_before(ts, data, w), bool)
            ts = ts[keep]
            data = {f: v[keep] for f, v in data.items()}
            # eviction is permanent (the reference mutates the
            # ListState): survivors replace the buffer
            kept_ix = np.nonzero(keep)[0]
            buf.ts = [buf.ts[j] for j in kept_ix]
            buf.data = [buf.data[j] for j in kept_ix]
        if not len(ts):
            return
        res = self.window_fn({**data, "__ts__": ts})
        row = {"key": np.asarray([key], np.int64),
               "window_start": np.asarray([w.start], np.int64),
               "window_end": np.asarray([w.end], np.int64)}
        for f, v in res.items():
            row[f] = np.asarray([v])
        self._emitted.append(row)
        buf.fired = True
        buf.trig_count = 0
        if purge:
            buf.ts, buf.data = [], []

    # -- time plane ------------------------------------------------------

    def advance_watermark(self, wm: int):
        from flink_tpu.ops.window import FiredWindows

        if wm > self.watermark:
            prev, self.watermark = self.watermark, wm
            for (k, w), buf in sorted(
                    self._bufs.items(),
                    key=lambda kv: (kv[0][1].end, kv[0][0])):
                if prev < w.end - 1 <= wm and buf.ts:
                    r = self.trigger.on_event_time(wm, w)
                    if r in (TriggerResult.FIRE,
                             TriggerResult.FIRE_AND_PURGE):
                        self._fire(
                            k, w, buf,
                            purge=(r == TriggerResult.FIRE_AND_PURGE))
            # purge dead windows past the lateness horizon
            dead = [kw for kw in self._bufs
                    if kw[1].end - 1 + self.lateness <= wm]
            for kw in dead:
                del self._bufs[kw]
        return FiredWindows(data=self._drain())

    def take_fired(self):
        from flink_tpu.ops.window import FiredWindows

        if not self._emitted:
            return None
        return FiredWindows(data=self._drain())

    def _drain(self) -> Dict[str, np.ndarray]:
        if not self._emitted:
            return {"key": np.zeros(0, np.int64),
                    "window_start": np.zeros(0, np.int64),
                    "window_end": np.zeros(0, np.int64)}
        parts, self._emitted = self._emitted, []
        return {f: np.concatenate([p[f] for p in parts])
                for f in parts[0]}

    def final_watermark(self) -> int:
        ends = [w.end for (_, w) in self._bufs]
        base = self.watermark if self.watermark != LONG_MIN else 0
        return max([base] + [e for e in ends])

    def quiesce(self) -> None:
        pass

    def throttle(self) -> None:
        pass

    # -- snapshot seam ---------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        bufs = []
        for (k, w), b in self._bufs.items():
            bufs.append({
                "key": k, "start": w.start, "end": w.end,
                "fired": b.fired,
                "trig_count": b.trig_count,
                "ts": np.asarray(b.ts, np.int64),
                "fields": ({f: np.asarray([r[f] for r in b.data])
                            for f in b.data[0]} if b.data and b.data[0]
                           else {}),
            })
        return {"kind": "evicting_window", "watermark": self.watermark,
                "late_records": self.late_records, "bufs": bufs}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.watermark = snap["watermark"]
        self.late_records = snap["late_records"]
        self._bufs = {}
        for e in snap["bufs"]:
            b = _Buf()
            b.fired = bool(e["fired"])
            b.trig_count = int(e.get("trig_count", 0))
            b.ts = [int(t) for t in np.asarray(e["ts"])]
            fields = e["fields"]
            names = list(fields)
            b.data = [{f: np.asarray(fields[f])[i] for f in names}
                      for i in range(len(b.ts))]
            self._bufs[(int(e["key"]),
                        TimeWindow(int(e["start"]), int(e["end"])))] = b
        self._emitted = []
