from flink_tpu.ops.aggregates import (
    LaneAggregate,
    count,
    sum_of,
    max_of,
    min_of,
    avg_of,
    multi,
    lower_aggregate,
)

__all__ = [
    "LaneAggregate",
    "count",
    "sum_of",
    "max_of",
    "min_of",
    "avg_of",
    "multi",
    "lower_aggregate",
]
