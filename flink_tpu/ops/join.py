"""Windowed equi-join — the Nexmark Q8 shape.

ref: streaming/api/datastream/{JoinedStreams,CoGroupedStreams}.java —
the reference lowers join(a,b).where(k).equalTo(k).window(w) onto a
WindowOperator over the union of both inputs, buffering raw elements in
ListState and emitting the CROSS PRODUCT of left×right per (key, window)
at fire time.

TPU-first redesign: raw-element buffers and dynamic cross products are
hostile to static shapes, and the benchmark joins (Q8: person ⋈ their
auctions) are effectively aggregate joins. So each side folds into its
own dense pane-state family (same layout as the window operator), and a
fire emits ONE row per (key, window) present on BOTH sides, carrying
each side's aggregated lanes (count + selected field aggregates).
Multiplicity-expanded cross products, when truly needed, are a host-side
expansion of these aggregate rows (deferred; the count lanes carry the
multiplicities)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.api.windowing import WindowAssigner
from flink_tpu.ops import aggregates
from flink_tpu.ops.window import FiredWindows, WindowOperator
from flink_tpu.time.watermarks import LONG_MIN


def _side_agg(fields: Sequence[str], prefix: str) -> aggregates.LaneAggregate:
    """count + a max-lane carry per selected field (for single-valued
    fields per (key, window) — the Q8 case — max IS the value; for
    multi-valued it is a deterministic representative)."""
    aggs = [aggregates.count(f"{prefix}count")]
    for f in fields:
        aggs.append(aggregates.max_of(f, f"{prefix}{f}"))
    return aggregates.multi(*aggs)


class WindowJoinOperator:
    """Two keyed window aggregations joined on (key, window) at fire time.

    The two sides share the watermark clock (the reference's two-input
    operator takes min over both inputs' watermarks — done by the driver
    before calling advance_watermark)."""

    def __init__(
        self,
        assigner: WindowAssigner,
        *,
        left_fields: Sequence[str] = (),
        right_fields: Sequence[str] = (),
        num_shards: int = 128,
        slots_per_shard: int = 1024,
        max_out_of_orderness_ms: int = 0,
        allowed_lateness_ms: int = 0,
    ) -> None:
        kw = dict(
            num_shards=num_shards, slots_per_shard=slots_per_shard,
            max_out_of_orderness_ms=max_out_of_orderness_ms,
            allowed_lateness_ms=allowed_lateness_ms,
        )
        self.left = WindowOperator(assigner, _side_agg(left_fields, "left_"), **kw)
        self.right = WindowOperator(assigner, _side_agg(right_fields, "right_"), **kw)
        self.left_fields = tuple(left_fields)
        self.right_fields = tuple(right_fields)

    @property
    def watermark(self) -> int:
        return min(self.left.watermark, self.right.watermark)

    def process_left(self, keys, ts, data, valid=None) -> None:
        # only configured fields reach the device (passthrough columns —
        # strings in particular — must not hit the pane kernels)
        self.left.process_batch(
            keys, ts, {f: data[f] for f in self.left_fields}, valid)

    def process_right(self, keys, ts, data, valid=None) -> None:
        self.right.process_batch(
            keys, ts, {f: data[f] for f in self.right_fields}, valid)

    def advance_watermark(self, wm: int) -> FiredWindows:
        # a late record on ONE side must re-emit the joined row, so both
        # sides re-fire the union of affected windows (ref role: the
        # merged WindowOperator fires once for the unioned input)
        union_refire = self.left._refire | self.right._refire
        self.left._refire = set(union_refire)
        self.right._refire = set(union_refire)
        fl = self.left.advance_watermark(wm)
        fr = self.right.advance_watermark(wm)

        def merge() -> Dict[str, np.ndarray]:
            # both sides fetch in ONE device→host round trip
            FiredWindows.materialize_many([fl, fr])
            l = fl.materialize()
            r = fr.materialize()
            # vectorized (key, window_end) inner match — the emit path
            # must stay off per-row Python (same rule as the fire kernel)
            lp = np.stack([l["key"], l["window_end"]], axis=1)
            rp = np.stack([r["key"], r["window_end"]], axis=1)
            uniq, inv = np.unique(np.concatenate([lp, rp]), axis=0,
                                  return_inverse=True)
            linv, rinv = inv[: len(lp)], inv[len(lp):]
            pos = np.full(len(uniq), -1, dtype=np.int64)
            pos[linv] = np.arange(len(lp))
            match = pos[rinv] >= 0
            ri = np.nonzero(match)[0]
            li = pos[rinv[match]]
            out: Dict[str, np.ndarray] = {
                "key": l["key"][li] if len(li) else np.zeros(0, np.int64),
                "window_start": l["window_start"][li] if len(li) else np.zeros(0, np.int64),
                "window_end": l["window_end"][li] if len(li) else np.zeros(0, np.int64),
            }
            for f in ("left_count",) + tuple(f"left_{x}" for x in self.left_fields):
                out[f] = l[f][li] if len(li) else np.zeros(0)
            for f in ("right_count",) + tuple(f"right_{x}" for x in self.right_fields):
                out[f] = r[f][ri] if len(ri) else np.zeros(0)
            return out

        return FiredWindows(fetch=merge)

    def final_watermark(self) -> int:
        return max(self.left.final_watermark(), self.right.final_watermark())

    def snapshot_state(self) -> Dict[str, Any]:
        return {"left": self.left.snapshot_state(),
                "right": self.right.snapshot_state()}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.left.restore_state(snap["left"])
        self.right.restore_state(snap["right"])
