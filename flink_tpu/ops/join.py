"""Windowed equi-join — the Nexmark Q8 shape.

ref: streaming/api/datastream/{JoinedStreams,CoGroupedStreams}.java —
the reference lowers join(a,b).where(k).equalTo(k).window(w) onto a
WindowOperator over the union of both inputs, buffering raw elements in
ListState and emitting the CROSS PRODUCT of left×right per (key, window)
at fire time.

Two lowerings, chosen per job:

- ``mode="pairs"`` (default — the reference's exact JoinFunction
  semantics): each side buffers its rows HOST-SIDE in columnar chunks
  (key, pane, fields), and a fire emits one row per matching left×right
  pair, expanded with vectorized ragged-group arithmetic (no per-pair
  Python). Raw-row retention is row-buffer work, which measurement puts
  on the host: rows would only cross the ~25-35 MB/s device link to be
  echoed back at fire time, while host numpy moves them at GB/s (same
  rationale as ops/window_all.py). Fire/lateness/refire semantics ride
  the shared WindowPlan control-plane helpers.

- ``mode="aggregate"``: each side folds into dense device pane-state
  (count + max-carry per field) and a fire emits ONE row per
  (key, window) present on both sides — the cogroup-style aggregate
  join, O(keys) output instead of O(pairs), for pipelines that only
  need per-key-window summaries.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.api.windowing import WindowAssigner
from flink_tpu.ops import aggregates
from flink_tpu.ops.host_control import HostPaneControl
from flink_tpu.ops.window import FiredWindows, WindowOperator, WindowPlan


def _side_agg(fields: Sequence[str], prefix: str) -> aggregates.LaneAggregate:
    """count + a max-lane carry per selected field (for single-valued
    fields per (key, window) — max IS the value; for multi-valued it is
    a deterministic representative)."""
    aggs = [aggregates.count(f"{prefix}count")]
    for f in fields:
        aggs.append(aggregates.max_of(f, f"{prefix}{f}"))
    return aggregates.multi(*aggs)


class _SideBuffer:
    """Host-side columnar row buffer for one join input: append-only
    chunks consolidated lazily, purged at the lateness horizon."""

    def __init__(self, fields: Sequence[str]) -> None:
        self.fields = tuple(fields)
        self._chunks: List[Tuple[np.ndarray, np.ndarray, Dict[str, np.ndarray]]] = []
        self._flat: Optional[Tuple[np.ndarray, np.ndarray, Dict[str, np.ndarray]]] = None

    def absorb(self, panes: np.ndarray, keys: np.ndarray,
               data: Dict[str, np.ndarray]) -> None:
        if len(panes) == 0:
            return
        self._chunks.append(
            (panes.copy(), keys.copy(),
             {f: np.asarray(data[f]).copy() for f in self.fields}))
        self._flat = None

    def _consolidated(self):
        if self._flat is None:
            if not self._chunks:
                self._flat = (np.zeros(0, np.int64), np.zeros(0, np.int64),
                              {f: np.zeros(0) for f in self.fields})
            else:
                panes = np.concatenate([c[0] for c in self._chunks])
                keys = np.concatenate([c[1] for c in self._chunks])
                cols = {f: np.concatenate([c[2][f] for c in self._chunks])
                        for f in self.fields}
                self._flat = (panes, keys, cols)
                self._chunks = [self._flat]
        return self._flat

    def rows_in_window(self, end_pane: int, ppw: int):
        panes, keys, cols = self._consolidated()
        m = (panes >= end_pane - ppw) & (panes < end_pane)
        return keys[m], {f: v[m] for f, v in cols.items()}

    def purge_below(self, dead_pane: int) -> None:
        panes, keys, cols = self._consolidated()
        keep = panes >= dead_pane
        if not keep.all():
            self._chunks = [(panes[keep], keys[keep],
                             {f: v[keep] for f, v in cols.items()})]
            self._flat = self._chunks[0]

    def snapshot(self) -> Dict[str, Any]:
        panes, keys, cols = self._consolidated()
        return {"panes": panes.copy(), "keys": keys.copy(),
                "cols": {f: v.copy() for f, v in cols.items()}}

    def restore(self, snap: Dict[str, Any]) -> None:
        self._chunks = [(np.array(snap["panes"]), np.array(snap["keys"]),
                         {f: np.array(v) for f, v in snap["cols"].items()})]
        self._flat = self._chunks[0]


def _cross_join_per_key(lk, lcols, rk, rcols, lf, rf,
                        max_pairs: Optional[int] = None):
    """One output row per matching left×right pair, grouped by key —
    fully vectorized ragged expansion (no per-pair Python). The
    ``max_pairs`` budget is checked BEFORE any expansion arrays are
    allocated — a pair explosion must die with a loud RuntimeError, not
    an OOM while materializing the thing the guard exists to prevent."""
    lo = np.argsort(lk, kind="stable")
    ro = np.argsort(rk, kind="stable")
    lks, rks = lk[lo], rk[ro]
    ul, l_start, l_cnt = np.unique(lks, return_index=True, return_counts=True)
    ur, r_start, r_cnt = np.unique(rks, return_index=True, return_counts=True)
    common, li, ri = np.intersect1d(ul, ur, return_indices=True)
    if len(common) == 0:
        return (np.zeros(0, np.int64),
                {f: np.zeros(0) for f in lf}, {f: np.zeros(0) for f in rf})
    nl, nr = l_cnt[li].astype(np.int64), r_cnt[ri].astype(np.int64)
    pairs = nl * nr
    total = int(pairs.sum())
    if max_pairs is not None and total > max_pairs:
        raise RuntimeError(
            f"join pair explosion: {total} pairs in one window fire "
            f"exceed the {max_pairs} budget; aggregate first or use "
            "mode='aggregate'")
    g = np.repeat(np.arange(len(common)), pairs)
    off = np.repeat(np.concatenate(([0], np.cumsum(pairs)[:-1])), pairs)
    within = np.arange(total) - off
    a = within // nr[g]          # left row within the key group
    b = within % nr[g]           # right row within the key group
    lidx = lo[l_start[li][g] + a]
    ridx = ro[r_start[ri][g] + b]
    return (common[g],
            {f: np.asarray(lcols[f])[lidx] for f in lf},
            {f: np.asarray(rcols[f])[ridx] for f in rf})


class WindowJoinOperator:
    """Two keyed inputs joined per (key, window) at fire time.

    The two sides share the watermark clock (the reference's two-input
    operator takes min over both inputs' watermarks — done by the driver
    before calling advance_watermark)."""

    #: loud guard against cross-product explosions (the same blow-up the
    #: reference's ListState join can hit, made explicit)
    MAX_PAIRS_PER_FIRE = 10_000_000

    def __init__(
        self,
        assigner: WindowAssigner,
        *,
        left_fields: Sequence[str] = (),
        right_fields: Sequence[str] = (),
        num_shards: int = 128,
        slots_per_shard: int = 1024,
        max_out_of_orderness_ms: int = 0,
        allowed_lateness_ms: int = 0,
        mode: str = "pairs",
    ) -> None:
        if mode not in ("pairs", "aggregate"):
            raise ValueError(
                f"join mode must be 'pairs' or 'aggregate', got {mode!r}")
        self.mode = mode
        self.left_fields = tuple(left_fields)
        self.right_fields = tuple(right_fields)
        self.state_version = 0
        if mode == "aggregate":
            kw = dict(
                num_shards=num_shards, slots_per_shard=slots_per_shard,
                max_out_of_orderness_ms=max_out_of_orderness_ms,
                allowed_lateness_ms=allowed_lateness_ms,
            )
            self.left = WindowOperator(assigner, _side_agg(left_fields, "left_"), **kw)
            self.right = WindowOperator(assigner, _side_agg(right_fields, "right_"), **kw)
            return
        self.plan = WindowPlan.plan(
            assigner, allowed_lateness_ms=allowed_lateness_ms,
            max_out_of_orderness_ms=max_out_of_orderness_ms)
        self._lbuf = _SideBuffer(left_fields)
        self._rbuf = _SideBuffer(right_fields)
        self.ctl = HostPaneControl(self.plan)
        self._empty_cache: Optional[Dict[str, np.ndarray]] = None

    @property
    def watermark(self) -> int:
        if self.mode == "aggregate":
            return min(self.left.watermark, self.right.watermark)
        return self.ctl.watermark

    @property
    def late_records(self) -> int:
        if self.mode == "aggregate":
            return self.left.late_records + self.right.late_records
        return self.ctl.late_records

    # -- ingest ----------------------------------------------------------

    def process_left(self, keys, ts, data, valid=None) -> None:
        self.state_version += 1
        if self.mode == "aggregate":
            self.left.process_batch(
                keys, ts, {f: data[f] for f in self.left_fields}, valid)
            return
        self._absorb(self._lbuf, keys, ts, data, valid)

    def process_right(self, keys, ts, data, valid=None) -> None:
        self.state_version += 1
        if self.mode == "aggregate":
            self.right.process_batch(
                keys, ts, {f: data[f] for f in self.right_fields}, valid)
            return
        self._absorb(self._rbuf, keys, ts, data, valid)

    def _absorb(self, buf: _SideBuffer, keys, ts, data, valid) -> None:
        keys = np.asarray(keys, np.int64)
        ts = np.asarray(ts, np.int64)
        valid = np.ones(len(ts), bool) if valid is None else np.asarray(valid, bool)
        # shared rule incl. refire: a late-but-allowed row on EITHER
        # side re-fires the joined window with the full updated pair set
        panes, valid = self.ctl.absorb_panes(ts, valid)
        if not valid.any():
            return
        buf.absorb(panes[valid], keys[valid],
                   {f: np.asarray(data[f])[valid] for f in buf.fields})

    # -- time ------------------------------------------------------------

    def advance_watermark(self, wm: int) -> FiredWindows:
        if self.mode == "aggregate":
            return self._advance_aggregate(wm)
        ends = self.ctl.begin_advance(wm)
        if ends is None:
            return self._empty()
        self.state_version += 1
        ppw = self.plan.panes_per_window
        out_parts: List[Dict[str, np.ndarray]] = []
        total_pairs = 0
        for e in ends:
            lk, lcols = self._lbuf.rows_in_window(e, ppw)
            if len(lk) == 0:
                continue
            rk, rcols = self._rbuf.rows_in_window(e, ppw)
            if len(rk) == 0:
                continue
            keys, lvals, rvals = _cross_join_per_key(
                lk, lcols, rk, rcols, self.left_fields, self.right_fields,
                max_pairs=self.MAX_PAIRS_PER_FIRE - total_pairs)
            n = len(keys)
            if n == 0:
                continue
            total_pairs += n
            we = e * self.plan.pane_ms + self.plan.offset_ms
            part: Dict[str, np.ndarray] = {
                "key": keys,
                "window_start": np.full(n, we - self.plan.size_ms, np.int64),
                "window_end": np.full(n, we, np.int64),
            }
            for f in self.left_fields:
                part[f"left_{f}"] = lvals[f]
            for f in self.right_fields:
                part[f"right_{f}"] = rvals[f]
            out_parts.append(part)

        new_dead = self.ctl.purge_horizon(wm)
        if new_dead is not None:
            self._lbuf.purge_below(new_dead)
            self._rbuf.purge_below(new_dead)
        if not out_parts:
            return self._empty()
        out = {k: np.concatenate([p[k] for p in out_parts])
               for k in out_parts[0]}
        return FiredWindows(data=out)

    def _advance_aggregate(self, wm: int) -> FiredWindows:
        # a late record on ONE side must re-emit the joined row, so both
        # sides re-fire the union of affected windows (ref role: the
        # merged WindowOperator fires once for the unioned input)
        union_refire = self.left._refire | self.right._refire
        self.left._refire = set(union_refire)
        self.right._refire = set(union_refire)
        fl = self.left.advance_watermark(wm)
        fr = self.right.advance_watermark(wm)

        def merge() -> Dict[str, np.ndarray]:
            # both sides fetch in ONE device→host round trip
            FiredWindows.materialize_many([fl, fr])
            l = fl.materialize()
            r = fr.materialize()
            # vectorized (key, window_end) inner match — the emit path
            # must stay off per-row Python (same rule as the fire kernel)
            lp = np.stack([l["key"], l["window_end"]], axis=1)
            rp = np.stack([r["key"], r["window_end"]], axis=1)
            uniq, inv = np.unique(np.concatenate([lp, rp]), axis=0,
                                  return_inverse=True)
            linv, rinv = inv[: len(lp)], inv[len(lp):]
            pos = np.full(len(uniq), -1, dtype=np.int64)
            pos[linv] = np.arange(len(lp))
            match = pos[rinv] >= 0
            ri = np.nonzero(match)[0]
            li = pos[rinv[match]]
            out: Dict[str, np.ndarray] = {
                "key": l["key"][li] if len(li) else np.zeros(0, np.int64),
                "window_start": l["window_start"][li] if len(li) else np.zeros(0, np.int64),
                "window_end": l["window_end"][li] if len(li) else np.zeros(0, np.int64),
            }
            for f in ("left_count",) + tuple(f"left_{x}" for x in self.left_fields):
                out[f] = l[f][li] if len(li) else np.zeros(0)
            for f in ("right_count",) + tuple(f"right_{x}" for x in self.right_fields):
                out[f] = r[f][ri] if len(ri) else np.zeros(0)
            return out

        return FiredWindows(fetch=merge)

    def final_watermark(self) -> int:
        if self.mode == "aggregate":
            return max(self.left.final_watermark(),
                       self.right.final_watermark())
        return self.ctl.final_watermark()

    def quiesce(self) -> None:
        if self.mode == "aggregate":
            self.left.quiesce()
            self.right.quiesce()

    def throttle(self) -> None:
        pass

    def _empty(self) -> FiredWindows:
        if self._empty_cache is None:
            cache: Dict[str, np.ndarray] = {
                "key": np.zeros(0, np.int64),
                "window_start": np.zeros(0, np.int64),
                "window_end": np.zeros(0, np.int64),
            }
            for f in self.left_fields:
                cache[f"left_{f}"] = np.zeros(0)
            for f in self.right_fields:
                cache[f"right_{f}"] = np.zeros(0)
            self._empty_cache = cache
        return FiredWindows(data=dict(self._empty_cache))

    # -- snapshot seam ----------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        if self.mode == "aggregate":
            return {"mode": "aggregate",
                    "left": self.left.snapshot_state(),
                    "right": self.right.snapshot_state()}
        return {
            "mode": "pairs",
            "left": self._lbuf.snapshot(),
            "right": self._rbuf.snapshot(),
            **self.ctl.snapshot(),
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        if snap.get("mode", "aggregate") != self.mode:
            raise ValueError(
                f"join snapshot mode {snap.get('mode')!r} != operator "
                f"mode {self.mode!r}")
        if self.mode == "aggregate":
            self.left.restore_state(snap["left"])
            self.right.restore_state(snap["right"])
            return
        self._lbuf.restore(snap["left"])
        self._rbuf.restore(snap["right"])
        self.ctl.restore(snap)
