"""Async I/O operator — external enrichment without stalling ingest.

ref: streaming/api/operators/async/AsyncWaitOperator.java +
api/functions/async/AsyncFunction.java (asyncInvoke per record,
orderedWait/unorderedWait, capacity backpressure, timeout).

TPU-first redesign: the unit of async work is the MICROBATCH, not the
record — one `fn(data, ts) -> data'` call per batch on a worker pool
(an external lookup amortized over the whole batch is also how a sane
client batches its RPCs). Up to ``capacity`` batches are in flight;
``ordered=True`` releases results in arrival order (orderedWait),
``ordered=False`` as they complete (unorderedWait). The event-time
contract of the reference is preserved: a watermark never overtakes
records it arrived behind — the operator releases watermark w only
after every batch submitted before w has been emitted. Timeouts fail
the job loudly (the reference's default timeout behavior)."""
from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.time.watermarks import LONG_MIN

Batch = Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray]


class AsyncIOOperator:
    """Driver-facing async enrichment stage."""

    def __init__(self, fn: Callable[..., Dict[str, np.ndarray]],
                 *, capacity: int = 8, timeout_ms: int = 60_000,
                 ordered: bool = True, workers: Optional[int] = None) -> None:
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.fn = fn
        self.capacity = capacity
        self.timeout_s = timeout_ms / 1000.0
        self.ordered = ordered
        self._pool = ThreadPoolExecutor(
            max_workers=workers or capacity,
            thread_name_prefix="async-io")
        # (future, ts, valid, wm_at_submit, submit_time, seq)
        self._inflight: collections.deque = collections.deque()
        self._seq = 0
        self.watermark = LONG_MIN  # released watermark (never overtakes)
        self._input_wm = LONG_MIN

    def submit(self, batch: Batch, input_wm: int) -> None:
        """Called by the driver's push path — NEVER blocks (the caller
        holds the push lock; a wait here would stall the drain thread's
        sink deliveries behind the enrichment RPC). The capacity wait
        happens in ``throttle()``, which the ingest loop calls OUTSIDE
        the lock — the same discipline as the window operator's
        external_throttle."""
        data, ts, valid = batch
        fut = self._pool.submit(self.fn, dict(data), ts)
        self._inflight.append(
            (fut, ts, valid, input_wm, time.monotonic(), self._seq))
        self._seq += 1

    def throttle(self) -> None:
        """Capacity backpressure, outside the push lock: block on the
        oldest still-RUNNING batch while more than ``capacity`` overlap
        (ref: AsyncWaitOperator's capacity semaphore). Completed batches
        awaiting ordered release don't count — they cost no worker."""
        while True:
            running = [it for it in self._inflight if not it[0].done()]
            if len(running) <= self.capacity:
                return
            self._await(running[0])

    def note_watermark(self, wm: int) -> None:
        self._input_wm = max(self._input_wm, wm)
        if not self._inflight:
            self.watermark = self._input_wm

    def poll(self, drain: bool = False) -> List[Batch]:
        """Completed batches ready for downstream, honoring order mode;
        advances the released watermark to the input watermark captured
        before the OLDEST still-pending batch. ``drain`` blocks until
        everything in flight completes (end of input / barrier)."""
        out: List[Batch] = []
        if drain:
            for item in list(self._inflight):
                self._await(item)
        while self._inflight:
            if self.ordered:
                head = self._inflight[0]
                if not (head[0].done() or drain):
                    break
                self._inflight.popleft()
                out.append(self._finish(head))
            else:
                done = [it for it in self._inflight if it[0].done()]
                if not done:
                    break
                for it in done:
                    self._inflight.remove(it)
                    out.append(self._finish(it))
        if self._inflight:
            # watermark released only up to the oldest pending submit
            self.watermark = max(
                self.watermark,
                min(it[3] for it in self._inflight))
        else:
            self.watermark = max(self.watermark, self._input_wm)
        return out

    def _await(self, item) -> None:
        fut, _, _, _, t0, _ = item
        remaining = self.timeout_s - (time.monotonic() - t0)
        try:
            fut.result(timeout=max(remaining, 0.001))
        except TimeoutError:
            raise TimeoutError(
                f"async I/O batch exceeded {self.timeout_s * 1000:.0f}ms "
                "timeout") from None

    def _finish(self, item) -> Batch:
        fut, ts, valid, _, t0, _ = item
        self._await(item)
        data = fut.result()  # re-raises the user fn's exception
        n = len(ts)
        for k, v in data.items():
            if len(np.asarray(v)) != n:
                raise ValueError(
                    f"async fn changed batch length for field {k!r}: "
                    f"{len(np.asarray(v))} != {n} (1:1 enrichment "
                    "contract)")
        return (data, ts, valid)

    @property
    def pending(self) -> int:
        return len(self._inflight)

    # -- snapshot seam: the driver's checkpoint barrier drains every
    # in-flight batch downstream BEFORE snapshotting, so this operator
    # is stateless at snapshot time by construction
    state_version = 0  # constant: the (empty) snapshot never changes

    def snapshot_state(self):
        assert not self._inflight, \
            "checkpoint barrier must drain async I/O first"
        return {"kind": "async_io"}

    def restore_state(self, snap) -> None:
        self._inflight.clear()

    def close(self) -> None:
        self._pool.shutdown(wait=False)


class AsyncFunction:
    """User interface (ref: api/functions/async/AsyncFunction.java) —
    batch form: override ``invoke_batch(data, ts) -> data'`` performing
    the external lookup for a whole microbatch; return the enriched
    struct-of-arrays (same length, 1:1)."""

    def invoke_batch(self, data: Dict[str, np.ndarray],
                     ts: np.ndarray) -> Dict[str, np.ndarray]:
        raise NotImplementedError
