"""Unwindowed keyed running aggregation — the UPSERT/changelog path.

ref: table/runtime aggregate/GroupAggFunction + the retract/changelog
stream model (SURVEY §3.8): `SELECT k, agg FROM t GROUP BY k` with no
window emits an ever-updating result per key. For INSERT-ONLY input
(the streaming source contract here) the changelog degenerates to an
UPSERT stream — each emitted row REPLACES the previous row for its
key. Sinks consume it either raw (`FnSink` sees every upsert — the
kafka-upsert shape) or materialized (`UpsertSink` keeps latest-by-key).

``retract=True`` emits the FULL changelog instead (ref: the retract
stream of SURVEY §3.8, RowKind-typed rows): each update becomes a
``-U`` row carrying the previously emitted values followed by a
``+U`` replacement (first emission: ``+I``), op-typed via the
``records.OP_FIELD`` int8 lane. This is what downstream changelog
consumers need — window aggregation that SUBTRACTS retracted rows
(ops/aggregates.changelog_* lanes), `RetractSink`, and the SQL
HAVING-over-unwindowed-aggregation rewrite all fold these rows.

TPU-first shape: per-key accumulators live in flat host arrays behind
the same KeyDirectory slot map the pane backend uses; a batch folds in
with one argsort + reduceat per lane (no per-record Python), and the
upserts emitted per microbatch are exactly the keys the batch touched
— the mini-batch aggregation emission model (ref: table-runtime
MiniBatchGroupAggFunction).
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from flink_tpu import faults
from flink_tpu.ops.window import FiredWindows, account_full_drop
from flink_tpu.records import (
    OP_DTYPE,
    OP_FIELD,
    OP_INSERT,
    OP_UPDATE_AFTER,
    OP_UPDATE_BEFORE,
)
from flink_tpu.state.keyed import KeyDirectory
from flink_tpu.time.watermarks import LONG_MIN


class GlobalAggregateOperator:
    """Driver-protocol operator: per-step upsert emission via
    ``take_fired`` (the count_window/process emission pattern).

    ``retract=True`` switches the output from the degenerate upsert
    stream to the full changelog (ref: GroupAggFunction's
    generateUpdateBefore path): a touched key whose result was emitted
    before first RETRACTS the stale row (``-U``, finalized from the
    accumulators as they stood at the previous emission) and then emits
    the replacement (``+U``); a key's first result is ``+I``. Rows carry
    the op type in the ``__op__`` int8 column (records.OP_FIELD). The
    ``-U`` block precedes the ``+I/+U`` block within one emission — a
    key appears at most once in each, so per-key changelog order holds.
    """

    def __init__(self, agg, *, num_shards: int,
                 slots_per_shard: int, retract: bool = False) -> None:
        self.agg = agg
        self.retract = bool(retract)
        self.directory = KeyDirectory(num_shards, slots_per_shard)
        n = self.directory.local_slots
        self.counts = np.zeros(n, np.int64)
        self.sums = np.zeros((n, agg.sum_width), np.float64)
        self.maxs = np.full((n, agg.max_width), -np.inf, np.float32)
        self.mins = np.full((n, agg.min_width), np.inf, np.float32)
        if self.retract:
            # accumulators AS EMITTED — the -U row's payload; a slot
            # retracts only after its first emission (emitted mask)
            self.prev_counts = np.zeros(n, np.int64)
            self.prev_sums = np.zeros((n, agg.sum_width), np.float64)
            self.prev_maxs = np.full((n, agg.max_width), -np.inf,
                                     np.float32)
            self.prev_mins = np.full((n, agg.min_width), np.inf,
                                     np.float32)
            self.emitted = np.zeros(n, bool)
        self.watermark = LONG_MIN
        self.late_records = 0          # unwindowed: nothing is late
        self.records_dropped_full = 0
        self.allow_drops = False
        self.state_version = 0
        self._touched: Optional[np.ndarray] = None

    # -- data plane ------------------------------------------------------

    def process_batch(self, keys, ts, data: Dict[str, np.ndarray],
                      valid=None) -> None:
        self.state_version += 1
        keys = np.asarray(keys, np.int64)
        valid = (np.ones(len(keys), bool) if valid is None
                 else np.asarray(valid, bool))
        if not valid.any():
            return
        keys = keys[valid]
        data = {k: np.asarray(v)[valid] for k, v in data.items()}
        slots = self.directory.assign(keys)
        bad = slots < 0
        if bad.any():
            account_full_drop(self, int(bad.sum()))
            keys, slots = keys[~bad], slots[~bad]
            data = {k: v[~bad] for k, v in data.items()}
            if not len(keys):
                return
        order = np.argsort(slots, kind="stable")
        so = slots[order]
        bnd = np.empty(len(so), bool)
        bnd[0] = True
        bnd[1:] = so[1:] != so[:-1]
        starts = np.nonzero(bnd)[0]
        uslots = so[starts]
        self.counts[uslots] += np.add.reduceat(
            np.ones(len(so), np.int64), starts)
        if self.agg.sum_width or self.agg.max_width or self.agg.min_width:
            s_l, mx_l, mn_l = self.agg.lift_masked(
                {k: v[order] for k, v in data.items()},
                np.ones(len(so), bool))
            s_l, mx_l, mn_l = (np.asarray(s_l), np.asarray(mx_l),
                               np.asarray(mn_l))
            if self.agg.sum_width:
                self.sums[uslots] += np.add.reduceat(s_l, starts, axis=0)
            if self.agg.max_width:
                self.maxs[uslots] = np.maximum(
                    self.maxs[uslots],
                    np.maximum.reduceat(mx_l, starts, axis=0))
            if self.agg.min_width:
                self.mins[uslots] = np.minimum(
                    self.mins[uslots],
                    np.minimum.reduceat(mn_l, starts, axis=0))
        self._touched = (uslots if self._touched is None
                         else np.union1d(self._touched, uslots))

    def take_fired(self) -> Optional["FiredWindows"]:
        """Emit the upsert rows for every key this step touched (or the
        -U/+U changelog pairs in retract mode)."""
        if self._touched is None or not len(self._touched):
            self._touched = None
            return None
        sl = self._touched
        self._touched = None
        wm = self.watermark if self.watermark != LONG_MIN else 0
        if not self.retract:
            res = self.agg.finalize(
                self.sums[sl].astype(np.float32), self.maxs[sl],
                self.mins[sl], self.counts[sl])
            out: Dict[str, np.ndarray] = {
                "key": self.directory.key_of_slots(sl)}
            out["count"] = self.counts[sl]
            for k, v in res.items():
                out[k] = np.asarray(v)
            # upserts carry the emission-time watermark as their
            # timestamp (the process-function emission contract,
            # driver _emit_fired)
            out["__ts__"] = np.full(len(sl), wm, np.int64)
            return FiredWindows(data=out)
        # retract mode: fired BEFORE any emission bookkeeping mutates,
        # so an injected failure here leaves (prev_*, emitted) exactly
        # as the last successful emission left them — recovery replays
        # the whole step and the changelog stays consistent
        faults.fire("changelog.retract.emit", exc=RuntimeError,
                    touched=len(sl))
        retr = sl[self.emitted[sl]]
        keys_new = self.directory.key_of_slots(sl)
        blocks = []
        if len(retr):
            res_old = self.agg.finalize(
                self.prev_sums[retr].astype(np.float32),
                self.prev_maxs[retr], self.prev_mins[retr],
                self.prev_counts[retr])
            old: Dict[str, np.ndarray] = {
                "key": self.directory.key_of_slots(retr),
                "count": self.prev_counts[retr]}
            for k, v in res_old.items():
                old[k] = np.asarray(v)
            old[OP_FIELD] = np.full(len(retr), OP_UPDATE_BEFORE,
                                    OP_DTYPE)
            blocks.append(old)
        res = self.agg.finalize(
            self.sums[sl].astype(np.float32), self.maxs[sl],
            self.mins[sl], self.counts[sl])
        new: Dict[str, np.ndarray] = {"key": keys_new,
                                      "count": self.counts[sl]}
        for k, v in res.items():
            new[k] = np.asarray(v)
        new[OP_FIELD] = np.where(self.emitted[sl], OP_UPDATE_AFTER,
                                 OP_INSERT).astype(OP_DTYPE)
        blocks.append(new)
        out = {k: np.concatenate([b[k] for b in blocks])
               for k in blocks[-1]}
        out["__ts__"] = np.full(len(out["key"]), wm, np.int64)
        # the emitted view is now the current accumulators
        self.prev_counts[sl] = self.counts[sl]
        self.prev_sums[sl] = self.sums[sl]
        self.prev_maxs[sl] = self.maxs[sl]
        self.prev_mins[sl] = self.mins[sl]
        self.emitted[sl] = True
        return FiredWindows(data=out)

    # -- time plane ------------------------------------------------------

    def advance_watermark(self, wm: int):
        if wm > self.watermark:
            self.watermark = wm
        return FiredWindows(data=dict(self._empty()))

    def _empty(self) -> Dict[str, np.ndarray]:
        res = self.agg.finalize(
            np.zeros((0, self.agg.sum_width), np.float32),
            np.zeros((0, self.agg.max_width), np.float32),
            np.zeros((0, self.agg.min_width), np.float32),
            np.zeros(0, np.int64))
        out = {"key": np.zeros(0, np.int64),
               "count": np.zeros(0, np.int64)}
        for k, v in res.items():
            out[k] = np.asarray(v)
        if self.retract:
            out[OP_FIELD] = np.zeros(0, OP_DTYPE)
        return out

    def final_watermark(self) -> int:
        return self.watermark if self.watermark != LONG_MIN else 0

    def quiesce(self) -> None:
        pass

    def throttle(self) -> None:
        pass

    # -- snapshot seam ---------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        snap = {
            "kind": "global_agg",
            "directory": self.directory.snapshot(),
            "counts": self.counts.copy(),
            "sums": self.sums.copy(),
            "maxs": self.maxs.copy(),
            "mins": self.mins.copy(),
            "watermark": self.watermark,
            "records_dropped_full": self.records_dropped_full,
        }
        if self.retract:
            snap["prev_counts"] = self.prev_counts.copy()
            snap["prev_sums"] = self.prev_sums.copy()
            snap["prev_maxs"] = self.prev_maxs.copy()
            snap["prev_mins"] = self.prev_mins.copy()
            snap["emitted"] = self.emitted.copy()
        return snap

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.directory = KeyDirectory.restore(
            self.directory.num_shards, self.directory.slots_per_shard,
            snap["directory"],
            (self.directory.shard_lo, self.directory.shard_hi))
        self.counts = np.asarray(snap["counts"]).copy()
        self.sums = np.asarray(snap["sums"]).copy()
        self.maxs = np.asarray(snap["maxs"]).copy()
        self.mins = np.asarray(snap["mins"]).copy()
        if self.retract:
            # a pre-retract snapshot restoring into a retract-mode op:
            # treat the restored view as already emitted so the first
            # post-restore update retracts it (no double +I)
            self.prev_counts = np.asarray(snap.get(
                "prev_counts", self.counts)).copy()
            self.prev_sums = np.asarray(snap.get(
                "prev_sums", self.sums)).copy()
            self.prev_maxs = np.asarray(snap.get(
                "prev_maxs", self.maxs)).copy()
            self.prev_mins = np.asarray(snap.get(
                "prev_mins", self.mins)).copy()
            self.emitted = np.asarray(snap.get(
                "emitted", self.counts > 0)).copy()
        self.watermark = snap["watermark"]
        self.records_dropped_full = snap.get("records_dropped_full", 0)
        self._touched = None
