"""Unwindowed keyed running aggregation — the UPSERT/changelog path.

ref: table/runtime aggregate/GroupAggFunction + the retract/changelog
stream model (SURVEY §3.8): `SELECT k, agg FROM t GROUP BY k` with no
window emits an ever-updating result per key. For INSERT-ONLY input
(the streaming source contract here) the changelog degenerates to an
UPSERT stream — each emitted row REPLACES the previous row for its
key, and no DELETE/retraction records are needed. Sinks consume it
either raw (`FnSink` sees every upsert — the kafka-upsert shape) or
materialized (`UpsertSink` keeps latest-by-key).

TPU-first shape: per-key accumulators live in flat host arrays behind
the same KeyDirectory slot map the pane backend uses; a batch folds in
with one argsort + reduceat per lane (no per-record Python), and the
upserts emitted per microbatch are exactly the keys the batch touched
— the mini-batch aggregation emission model (ref: table-runtime
MiniBatchGroupAggFunction).
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from flink_tpu.ops.window import FiredWindows, account_full_drop
from flink_tpu.state.keyed import KeyDirectory
from flink_tpu.time.watermarks import LONG_MIN


class GlobalAggregateOperator:
    """Driver-protocol operator: per-step upsert emission via
    ``take_fired`` (the count_window/process emission pattern)."""

    def __init__(self, agg, *, num_shards: int,
                 slots_per_shard: int) -> None:
        self.agg = agg
        self.directory = KeyDirectory(num_shards, slots_per_shard)
        n = self.directory.local_slots
        self.counts = np.zeros(n, np.int64)
        self.sums = np.zeros((n, agg.sum_width), np.float64)
        self.maxs = np.full((n, agg.max_width), -np.inf, np.float32)
        self.mins = np.full((n, agg.min_width), np.inf, np.float32)
        self.watermark = LONG_MIN
        self.late_records = 0          # unwindowed: nothing is late
        self.records_dropped_full = 0
        self.allow_drops = False
        self.state_version = 0
        self._touched: Optional[np.ndarray] = None

    # -- data plane ------------------------------------------------------

    def process_batch(self, keys, ts, data: Dict[str, np.ndarray],
                      valid=None) -> None:
        self.state_version += 1
        keys = np.asarray(keys, np.int64)
        valid = (np.ones(len(keys), bool) if valid is None
                 else np.asarray(valid, bool))
        if not valid.any():
            return
        keys = keys[valid]
        data = {k: np.asarray(v)[valid] for k, v in data.items()}
        slots = self.directory.assign(keys)
        bad = slots < 0
        if bad.any():
            account_full_drop(self, int(bad.sum()))
            keys, slots = keys[~bad], slots[~bad]
            data = {k: v[~bad] for k, v in data.items()}
            if not len(keys):
                return
        order = np.argsort(slots, kind="stable")
        so = slots[order]
        bnd = np.empty(len(so), bool)
        bnd[0] = True
        bnd[1:] = so[1:] != so[:-1]
        starts = np.nonzero(bnd)[0]
        uslots = so[starts]
        self.counts[uslots] += np.add.reduceat(
            np.ones(len(so), np.int64), starts)
        if self.agg.sum_width or self.agg.max_width or self.agg.min_width:
            s_l, mx_l, mn_l = self.agg.lift_masked(
                {k: v[order] for k, v in data.items()},
                np.ones(len(so), bool))
            s_l, mx_l, mn_l = (np.asarray(s_l), np.asarray(mx_l),
                               np.asarray(mn_l))
            if self.agg.sum_width:
                self.sums[uslots] += np.add.reduceat(s_l, starts, axis=0)
            if self.agg.max_width:
                self.maxs[uslots] = np.maximum(
                    self.maxs[uslots],
                    np.maximum.reduceat(mx_l, starts, axis=0))
            if self.agg.min_width:
                self.mins[uslots] = np.minimum(
                    self.mins[uslots],
                    np.minimum.reduceat(mn_l, starts, axis=0))
        self._touched = (uslots if self._touched is None
                         else np.union1d(self._touched, uslots))

    def take_fired(self) -> Optional["FiredWindows"]:
        """Emit the upsert rows for every key this step touched."""
        if self._touched is None or not len(self._touched):
            self._touched = None
            return None
        sl = self._touched
        self._touched = None
        res = self.agg.finalize(
            self.sums[sl].astype(np.float32), self.maxs[sl],
            self.mins[sl], self.counts[sl])
        out: Dict[str, np.ndarray] = {
            "key": self.directory.key_of_slots(sl)}
        out["count"] = self.counts[sl]
        for k, v in res.items():
            out[k] = np.asarray(v)
        # upserts carry the emission-time watermark as their timestamp
        # (the process-function emission contract, driver _emit_fired)
        wm = self.watermark if self.watermark != LONG_MIN else 0
        out["__ts__"] = np.full(len(sl), wm, np.int64)
        return FiredWindows(data=out)

    # -- time plane ------------------------------------------------------

    def advance_watermark(self, wm: int):
        if wm > self.watermark:
            self.watermark = wm
        return FiredWindows(data=dict(self._empty()))

    def _empty(self) -> Dict[str, np.ndarray]:
        res = self.agg.finalize(
            np.zeros((0, self.agg.sum_width), np.float32),
            np.zeros((0, self.agg.max_width), np.float32),
            np.zeros((0, self.agg.min_width), np.float32),
            np.zeros(0, np.int64))
        out = {"key": np.zeros(0, np.int64),
               "count": np.zeros(0, np.int64)}
        for k, v in res.items():
            out[k] = np.asarray(v)
        return out

    def final_watermark(self) -> int:
        return self.watermark if self.watermark != LONG_MIN else 0

    def quiesce(self) -> None:
        pass

    def throttle(self) -> None:
        pass

    # -- snapshot seam ---------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "kind": "global_agg",
            "directory": self.directory.snapshot(),
            "counts": self.counts.copy(),
            "sums": self.sums.copy(),
            "maxs": self.maxs.copy(),
            "mins": self.mins.copy(),
            "watermark": self.watermark,
            "records_dropped_full": self.records_dropped_full,
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.directory = KeyDirectory.restore(
            self.directory.num_shards, self.directory.slots_per_shard,
            snap["directory"],
            (self.directory.shard_lo, self.directory.shard_hi))
        self.counts = np.asarray(snap["counts"]).copy()
        self.sums = np.asarray(snap["sums"]).copy()
        self.maxs = np.asarray(snap["maxs"]).copy()
        self.mins = np.asarray(snap["mins"]).copy()
        self.watermark = snap["watermark"]
        self.records_dropped_full = snap.get("records_dropped_full", 0)
        self._touched = None
