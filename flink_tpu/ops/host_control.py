"""Shared host-side window control plane.

The host-resident windowed operators (the global ``WindowAllOperator``
and the pairs-mode ``WindowJoinOperator``) need the same state machine
the device ``WindowOperator`` runs: beyond-lateness filtering, pane
range tracking, late-within-lateness re-fire enumeration, the fired
frontier, and the purge horizon. The pane MATH lives on ``WindowPlan``
(ops/window.py); this class owns the mutable state around it so the
rule set exists exactly once — a semantic fix here changes every
host-side operator together instead of silently diverging per copy.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.ops.window import WindowPlan
from flink_tpu.time.watermarks import LONG_MIN


class HostPaneControl:
    """Late/refire/frontier/purge bookkeeping for one operator."""

    def __init__(self, plan: WindowPlan) -> None:
        self.plan = plan
        self.watermark = LONG_MIN
        self.late_records = 0
        self.refire: set[int] = set()
        self.cleared_below = plan.first_dead_pane(LONG_MIN)
        self.fired_below_end: Optional[int] = None
        self.min_pane_seen: Optional[int] = None
        self.max_pane_seen: Optional[int] = None

    # -- ingest side -----------------------------------------------------

    def absorb_panes(self, ts: np.ndarray, valid: np.ndarray):
        """Classify a batch: drop-with-accounting beyond lateness, track
        the written pane range, and mark re-fires for late-but-allowed
        records landing in already-fired windows. Returns the pane array
        and the surviving validity mask."""
        panes = self.plan.pane_of(ts)
        late = valid & (panes < self.cleared_below)
        self.late_records += int(late.sum())
        valid = valid & ~late
        if valid.any():
            mn, mx = int(panes[valid].min()), int(panes[valid].max())
            if self.min_pane_seen is None or mn < self.min_pane_seen:
                self.min_pane_seen = mn
            if self.max_pane_seen is None or mx > self.max_pane_seen:
                self.max_pane_seen = mx
            if self.fired_below_end is not None:
                late_ok = valid & (panes < self.fired_below_end)
                if late_ok.any():
                    self.refire.update(self.plan.late_refire_ends(
                        panes[late_ok], self.fired_below_end,
                        self.watermark))
        return panes, valid

    # -- time side -------------------------------------------------------

    def begin_advance(self, wm: int) -> Optional[List[int]]:
        """None when the advance is a no-op; otherwise the sorted list
        of end panes to fire (first-time firings ∪ pending re-fires),
        with the watermark, frontier, and refire set updated."""
        if wm < self.watermark or (wm == self.watermark and not self.refire):
            return None
        prev = self.watermark
        self.watermark = wm
        ends = sorted(set(self.plan.enumerate_fire_ends(
            prev, wm, self.min_pane_seen, self.max_pane_seen))
            | self.refire)
        frontier = self.plan.fire_frontier(wm)
        if self.fired_below_end is None or frontier > self.fired_below_end:
            self.fired_below_end = frontier
        self.refire.clear()
        return ends

    def purge_horizon(self, wm: int) -> Optional[int]:
        """The new first-dead pane when the horizon moved, else None.
        Callers drop state below the returned pane."""
        new_dead = self.plan.first_dead_pane(wm)
        if new_dead > self.cleared_below:
            self.cleared_below = new_dead
            return new_dead
        return None

    def final_watermark(self) -> int:
        return self.plan.final_watermark_for(
            self.watermark, self.max_pane_seen)

    # -- snapshot --------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        return {
            "watermark": self.watermark,
            "late_records": self.late_records,
            "refire": sorted(self.refire),
            "cleared_below": self.cleared_below,
            "fired_below_end": self.fired_below_end,
            "min_pane_seen": self.min_pane_seen,
            "max_pane_seen": self.max_pane_seen,
        }

    def restore(self, snap: Dict[str, Any]) -> None:
        self.watermark = snap["watermark"]
        self.late_records = snap["late_records"]
        self.refire = set(snap["refire"])
        self.cleared_below = snap["cleared_below"]
        self.fired_below_end = snap["fired_below_end"]
        self.min_pane_seen = snap["min_pane_seen"]
        self.max_pane_seen = snap["max_pane_seen"]
