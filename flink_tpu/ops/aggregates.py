"""Aggregate lowering: user aggregation → dense accumulator lanes.

The reference evaluates ``AggregateFunction.add`` once per element against
a per-(key, window) heap/RocksDB accumulator object (ref: flink-core/.../
api/common/functions/AggregateFunction.java, applied in streaming/runtime/
operators/windowing/WindowOperator.processElement via AggregatingState).

TPU-first redesign: accumulators become fixed-width **lanes** in a dense
``(slots, panes, width)`` tensor, and a whole microbatch is folded in with
three scatter ops (add / max / min) — one per combine class. Anything
expressible as per-lane sum/max/min composes freely: count, sum, avg
(sum+count), max, min, argmax-by-packing, etc. This covers every
BASELINE.json config. ``lower_aggregate`` adapts the reference-style
AggregateFunction class to this form when its merge is recognizably
per-leaf sum/max/min.

Invariants:
- identity elements: sum→0, max→-inf, min→+inf (padding rows lift to
  identities, so invalid records are no-ops).
- ``finalize`` maps lane vectors back to user-visible results and also
  receives the built-in count lane (number of elements in the cell).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

Arrays = Dict[str, jax.Array]

F32_NEG_INF = float("-inf")
F32_POS_INF = float("inf")


@dataclasses.dataclass(frozen=True)
class LaneAggregate:
    """A windowed aggregation as sum/max/min lanes.

    lift(data)  -> (sum (B,S), max (B,M), min (B,m)) per-record lane values
    finalize(sums, maxs, mins, counts) -> result dict; inputs have shape
    (..., width) / counts (...,) and must broadcast over leading dims.
    """

    sum_width: int
    max_width: int
    min_width: int
    lift: Callable[[Arrays], Tuple[jax.Array, jax.Array, jax.Array]]
    finalize: Callable[[jax.Array, jax.Array, jax.Array, jax.Array], Arrays]
    name: str = "agg"
    # record fields ``lift`` reads. The operator uploads ONLY these to
    # the device — on a remote-attached chip the host→device link is the
    # throughput ceiling, so unused lanes must never ride it (count()
    # uploads nothing but the packed slot ids). None = unknown: keep all.
    fields: Optional[Tuple[str, ...]] = None
    # When every sum lane is the IDENTITY lift of one record field
    # (lane i == f32(data[sum_fields[i]])), the host can pre-combine a
    # microbatch per (key, pane) pair with np.bincount before upload —
    # the mini-batch local-aggregation trick (ref: table/runtime
    # mini-batch agg, SURVEY §3.8) that shrinks both the host→device
    # bytes and the device scatter from records to distinct pairs.
    # None = lift is opaque; the operator must ship raw records.
    sum_fields: Optional[Tuple[str, ...]] = None

    def lift_masked(self, data: Arrays, valid: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Lift a batch, mapping invalid rows to identity elements.
        Normalizes shape to (B, width) even when lift can't know B
        (e.g. count() over a batch with no data fields)."""
        b = valid.shape[0]
        s, mx, mn = self.lift(data)

        v = valid[:, None]

        def norm(x, width, fill):
            if width == 0:
                return jnp.full((b, width), fill, dtype=jnp.float32)
            if x is None or x.ndim != 2 or x.shape[0] != b or x.shape[-1] != width:
                raise ValueError(
                    f"aggregate '{self.name}': lift returned shape "
                    f"{None if x is None else x.shape}, expected ({b}, {width})")
            return jnp.where(v, x, jnp.full_like(x, fill))

        return (
            norm(s, self.sum_width, 0.0),
            norm(mx, self.max_width, F32_NEG_INF),
            norm(mn, self.min_width, F32_POS_INF),
        )


def _empty_lanes(b: jax.Array) -> jax.Array:
    return jnp.zeros(b.shape[:1] + (0,), dtype=jnp.float32)


def probe_finalize(agg: LaneAggregate) -> Arrays:
    """``finalize`` evaluated on EMPTY lanes — THE result-field probe.
    Single-sourced here because three consumers must agree on the
    fired-row result columns: :func:`result_fields`, the compiler's
    ``ExecNode.out_schema`` recording (graph/compiler.py), and
    ``WindowOperator._result_fields``' dtype classification."""
    return agg.finalize(
        np.zeros((0, agg.sum_width), np.float32),
        np.zeros((0, agg.max_width), np.float32),
        np.zeros((0, agg.min_width), np.float32),
        np.zeros((0,), np.int32))


def result_fields(agg: LaneAggregate) -> Tuple[str, ...]:
    """The result-field names an aggregate's finalize produces (probed on
    empty lanes; mirrors WindowOperator._result_fields ordering)."""
    return tuple(sorted(probe_finalize(agg)))


def _cached(factory):
    """Memoize built-in aggregate factories so equal configurations share
    one LaneAggregate instance — and therefore one compiled kernel
    (jit caches key on the aggregate object)."""
    import functools

    return functools.lru_cache(maxsize=None)(factory)


@_cached
def count(result_field: str = "count") -> LaneAggregate:
    """COUNT(*) — pure count-lane read (Nexmark Q5's per-key COUNT).
    ref role: CountAggregator in windowed WordCount examples."""

    def lift(data: Arrays):
        b = next(iter(data.values())) if data else jnp.zeros((0,))
        z = _empty_lanes(b)
        return z, z, z

    def finalize(sums, maxs, mins, counts):
        return {result_field: counts}

    return LaneAggregate(0, 0, 0, lift, finalize, name="count", fields=(),
                         sum_fields=())


@_cached
def sum_of(field: str, result_field: Optional[str] = None) -> LaneAggregate:
    out = result_field or f"sum_{field}"

    def lift(data: Arrays):
        s = data[field].astype(jnp.float32)[:, None]
        z = _empty_lanes(data[field])
        return s, z, z

    def finalize(sums, maxs, mins, counts):
        return {out: sums[..., 0]}

    return LaneAggregate(1, 0, 0, lift, finalize, name=f"sum({field})",
                         fields=(field,), sum_fields=(field,))


@_cached
def max_of(field: str, result_field: Optional[str] = None) -> LaneAggregate:
    out = result_field or f"max_{field}"

    def lift(data: Arrays):
        m = data[field].astype(jnp.float32)[:, None]
        z = _empty_lanes(data[field])
        return z, m, z

    def finalize(sums, maxs, mins, counts):
        return {out: maxs[..., 0]}

    return LaneAggregate(0, 1, 0, lift, finalize, name=f"max({field})",
                         fields=(field,))


@_cached
def min_of(field: str, result_field: Optional[str] = None) -> LaneAggregate:
    out = result_field or f"min_{field}"

    def lift(data: Arrays):
        m = data[field].astype(jnp.float32)[:, None]
        z = _empty_lanes(data[field])
        return z, z, m

    def finalize(sums, maxs, mins, counts):
        return {out: mins[..., 0]}

    return LaneAggregate(0, 0, 1, lift, finalize, name=f"min({field})",
                         fields=(field,))


@_cached
def avg_of(field: str, result_field: Optional[str] = None) -> LaneAggregate:
    out = result_field or f"avg_{field}"

    def lift(data: Arrays):
        s = data[field].astype(jnp.float32)[:, None]
        z = _empty_lanes(data[field])
        return s, z, z

    def finalize(sums, maxs, mins, counts):
        c = jnp.maximum(counts, 1).astype(jnp.float32)
        return {out: sums[..., 0] / c}

    return LaneAggregate(1, 0, 0, lift, finalize, name=f"avg({field})",
                         fields=(field,), sum_fields=(field,))


@_cached
def multi(*aggs: LaneAggregate) -> LaneAggregate:
    """Compose several aggregations over one window into one lane layout
    (e.g. Q7 needs max(price); a dashboard wants count+sum+max at once)."""
    sw = sum(a.sum_width for a in aggs)
    mw = sum(a.max_width for a in aggs)
    nw = sum(a.min_width for a in aggs)

    def lift(data: Arrays):
        ss, ms, ns = [], [], []
        for a in aggs:
            s, m, n = a.lift(data)
            ss.append(s)
            ms.append(m)
            ns.append(n)
        return (
            jnp.concatenate(ss, axis=-1) if ss else None,
            jnp.concatenate(ms, axis=-1) if ms else None,
            jnp.concatenate(ns, axis=-1) if ns else None,
        )

    def finalize(sums, maxs, mins, counts):
        out: Arrays = {}
        so = mo = no = 0
        for a in aggs:
            r = a.finalize(
                sums[..., so : so + a.sum_width],
                maxs[..., mo : mo + a.max_width],
                mins[..., no : no + a.min_width],
                counts,
            )
            out.update(r)
            so += a.sum_width
            mo += a.max_width
            no += a.min_width
        return out

    comp_fields: Optional[Tuple[str, ...]] = ()
    for a in aggs:
        if a.fields is None:
            comp_fields = None
            break
        comp_fields = tuple(dict.fromkeys(comp_fields + a.fields))
    comp_sum: Optional[Tuple[str, ...]] = ()
    for a in aggs:
        if a.sum_fields is None:
            comp_sum = None
            break
        comp_sum = comp_sum + a.sum_fields
    return LaneAggregate(sw, mw, nw, lift, finalize,
                         name="+".join(a.name for a in aggs),
                         fields=comp_fields, sum_fields=comp_sum)


# ---------------------------------------------------------------------------
# Changelog-consuming lanes: windowed aggregation over op-typed input.
# ---------------------------------------------------------------------------

def _op_sign(data: Arrays) -> jax.Array:
    """Per-record +1/-1 from the changelog op column (records.OP_FIELD):
    +I/+U add, -U/-D subtract — retraction folding as arithmetic, the
    table-runtime ``retract()`` call vectorized into the lift (ref:
    table/runtime AggsHandleFunction.retract)."""
    from flink_tpu.records import OP_DELETE, OP_FIELD, OP_UPDATE_BEFORE

    ops = data[OP_FIELD].astype(jnp.int32)
    return jnp.where((ops == OP_UPDATE_BEFORE) | (ops == OP_DELETE),
                     -1.0, 1.0).astype(jnp.float32)


@_cached
def changelog_count(result_field: str = "count") -> LaneAggregate:
    """COUNT(*) over a changelog stream — each -U/-D row erases the +I/+U
    it supersedes, so the count is the SUM OF SIGNS, not the row count
    (the built-in count lane would double-count every update pair).
    Opaque lift (``sum_fields=None``): the sign is derived, not an
    identity field read, so the host bincount pre-agg stays off."""
    from flink_tpu.records import OP_FIELD

    def lift(data: Arrays):
        s = _op_sign(data)[:, None]
        z = _empty_lanes(s[:, 0])
        return s, z, z

    def finalize(sums, maxs, mins, counts):
        return {result_field: jnp.round(sums[..., 0]).astype(jnp.int32)}

    return LaneAggregate(1, 0, 0, lift, finalize, name="changelog_count",
                         fields=(OP_FIELD,))


@_cached
def changelog_sum_of(field: str,
                     result_field: Optional[str] = None) -> LaneAggregate:
    """SUM(field) over a changelog stream: sign-weighted values, so a
    -U retraction subtracts exactly what its +I/+U contributed."""
    from flink_tpu.records import OP_FIELD

    out = result_field or f"sum_{field}"

    def lift(data: Arrays):
        s = (data[field].astype(jnp.float32) * _op_sign(data))[:, None]
        z = _empty_lanes(data[field])
        return s, z, z

    def finalize(sums, maxs, mins, counts):
        return {out: sums[..., 0]}

    return LaneAggregate(1, 0, 0, lift, finalize,
                         name=f"changelog_sum({field})",
                         fields=(field, OP_FIELD))


@_cached
def changelog_avg_of(field: str,
                     result_field: Optional[str] = None) -> LaneAggregate:
    """AVG(field) over a changelog stream: signed sum / signed count —
    the operator's built-in count lane counts ROWS (retractions
    included), so the divisor must be a dedicated signed lane."""
    from flink_tpu.records import OP_FIELD

    out = result_field or f"avg_{field}"

    def lift(data: Arrays):
        sign = _op_sign(data)
        s = jnp.stack([data[field].astype(jnp.float32) * sign, sign],
                      axis=-1)
        z = _empty_lanes(data[field])
        return s, z, z

    def finalize(sums, maxs, mins, counts):
        c = jnp.maximum(jnp.round(sums[..., 1]), 1.0)
        return {out: sums[..., 0] / c}

    return LaneAggregate(2, 0, 0, lift, finalize,
                         name=f"changelog_avg({field})",
                         fields=(field, OP_FIELD))


def changelog_max_of(field: str, result_field: Optional[str] = None) -> None:
    """Refused: max is a monoid fold — it cannot retract. Once a value
    has raised the lane, subtracting its -U row cannot lower it back
    (that needs the full value multiset, i.e. an evicting window)."""
    raise NotImplementedError(
        "MAX over a changelog stream cannot retract: max(a, b) forgets "
        "the loser, so a -U row cannot undo its +U. Materialize the "
        "stream first (RetractSink / UpsertSink) or keep the raw rows "
        "with an evicting window.")


def changelog_min_of(field: str, result_field: Optional[str] = None) -> None:
    """Refused for the same reason as :func:`changelog_max_of`."""
    raise NotImplementedError(
        "MIN over a changelog stream cannot retract: min(a, b) forgets "
        "the loser, so a -U row cannot undo its +U. Materialize the "
        "stream first (RetractSink / UpsertSink) or keep the raw rows "
        "with an evicting window.")


# ---------------------------------------------------------------------------
# Lowering reference-style AggregateFunction classes.
# ---------------------------------------------------------------------------

def lower_aggregate(fn: Any, probe_fields: Dict[str, Any]) -> LaneAggregate:
    """Adapt a user AggregateFunction (create_accumulator/add/merge/
    get_result, ref: AggregateFunction.java) to the lane layout.

    Strategy: trace ``merge`` on symbolic accumulators and classify each
    accumulator leaf as sum-merged (a+b), max-merged, or min-merged by
    evaluating merge on probe values. Leaves that don't match any lane
    class are rejected — the caller should fall back to composing
    built-in lane aggregates (sum_of/max_of/...) or restructure.

    probe_fields: field name → numpy dtype, the record schema the
    aggregate will see (needed to build probe batches).
    """
    import numpy as np

    acc0 = fn.create_accumulator()
    leaves0, treedef = jax.tree_util.tree_flatten(acc0)

    # classify each leaf by behaviour of merge on probe numbers
    probes_a = [np.float64(3.0)] * len(leaves0)
    probes_b = [np.float64(5.0)] * len(leaves0)
    a = jax.tree_util.tree_unflatten(treedef, [jnp.asarray(p) for p in probes_a])
    b = jax.tree_util.tree_unflatten(treedef, [jnp.asarray(p) for p in probes_b])
    merged = fn.merge(a, b)
    mleaves = [float(x) for x in jax.tree_util.tree_leaves(merged)]

    kinds = []
    for m in mleaves:
        if abs(m - 8.0) < 1e-9:
            kinds.append("sum")
        elif abs(m - 5.0) < 1e-9:
            kinds.append("max")
        elif abs(m - 3.0) < 1e-9:
            kinds.append("min")
        else:
            raise NotImplementedError(
                f"accumulator leaf merges as neither sum/max/min (got {m} from "
                "merge(3,5)); compose flink_tpu.ops built-in lane aggregates "
                "instead")
    # disambiguate max vs min with a second probe (merge(5,3))
    a2 = jax.tree_util.tree_unflatten(treedef, [jnp.asarray(np.float64(5.0))] * len(leaves0))
    b2 = jax.tree_util.tree_unflatten(treedef, [jnp.asarray(np.float64(3.0))] * len(leaves0))
    m2 = [float(x) for x in jax.tree_util.tree_leaves(fn.merge(a2, b2))]
    for i, (k, v) in enumerate(zip(kinds, m2)):
        if k == "max" and abs(v - 5.0) > 1e-9:
            raise NotImplementedError("non-commutative merge")
        if k == "min" and abs(v - 3.0) > 1e-9:
            kinds[i] = "max"  # merge(3,5)=3? then (5,3)=5 would be 'first'; reject
            raise NotImplementedError("non-commutative merge")

    sum_ix = [i for i, k in enumerate(kinds) if k == "sum"]
    max_ix = [i for i, k in enumerate(kinds) if k == "max"]
    min_ix = [i for i, k in enumerate(kinds) if k == "min"]

    def lift(data: Arrays):
        # one vmapped add against a fresh accumulator lifts each record
        def one(row: Arrays):
            acc = fn.create_accumulator()
            return fn.add(row, acc)

        accs = jax.vmap(one)(data)
        leaves = jax.tree_util.tree_leaves(accs)
        cols = [l.astype(jnp.float32).reshape(l.shape[0], -1) for l in leaves]

        def gather(ix):
            if not ix:
                return jnp.zeros((cols[0].shape[0], 0), dtype=jnp.float32)
            return jnp.concatenate([cols[i] for i in ix], axis=-1)

        return gather(sum_ix), gather(max_ix), gather(min_ix)

    def finalize(sums, maxs, mins, counts):
        leaves = [None] * len(leaves0)
        for j, i in enumerate(sum_ix):
            leaves[i] = sums[..., j]
        for j, i in enumerate(max_ix):
            leaves[i] = maxs[..., j]
        for j, i in enumerate(min_ix):
            leaves[i] = mins[..., j]
        acc = jax.tree_util.tree_unflatten(treedef, leaves)
        res = fn.get_result(acc)
        if not isinstance(res, dict):
            res = {"result": res}
        return res

    return LaneAggregate(len(sum_ix), len(max_ix), len(min_ix), lift, finalize,
                         name=type(fn).__name__,
                         fields=tuple(probe_fields))
