"""Broadcast state pattern: a low-volume control stream joined with a
data stream.

ref: streaming/api/datastream/BroadcastConnectedStream.java +
api/operators/co/CoBroadcastWithNonKeyedOperator.java and the broadcast
state pattern (SURVEY §3.7 row 'Broadcast state'): control elements
replicate to every subtask and land in broadcast state; data elements
read that state.

TPU-first shape: the broadcast state is a SMALL host-side dict (the
replicated-small-tensor analogue — in SPMD execution every device sees
the same host-prepared state, so replication is free by construction),
and the data-side processing is BATCH-vectorized: the user function
receives whole column batches plus the current state and returns
column batches. Elements are processed in arrival order per stream;
like the reference, no cross-stream order is guaranteed.
"""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["BroadcastProcessFunction", "BroadcastConnectOperator"]

Batch = Dict[str, np.ndarray]


class BroadcastProcessFunction:
    """User function for a connected (data, broadcast) pair — the
    vectorized analogue of BroadcastProcessFunction's processElement /
    processBroadcastElement pair."""

    def process_element(self, data: Batch, ts: np.ndarray,
                        state: Dict[str, Any]) -> Optional[Batch]:
        """Data-side batch against the CURRENT broadcast state. Return
        an output batch (columns of equal length) or None."""
        raise NotImplementedError

    def process_broadcast_element(self, data: Batch, ts: np.ndarray,
                                  state: Dict[str, Any]) -> None:
        """Control-side batch: mutate the broadcast state in place."""
        raise NotImplementedError


class BroadcastConnectOperator:
    """Runtime operator for ``stream.connect(control).process(fn)``.
    Emits per step (no event-time timers in v1); broadcast state rides
    checkpoints so restores resume with the control decisions applied
    so far (ref: broadcast state is checkpointed operator state)."""

    def __init__(self, fn: BroadcastProcessFunction) -> None:
        self.fn = fn
        self.state: Dict[str, Any] = {}
        self._out: List[Batch] = []
        # incremental-checkpoint dirtiness marker (the driver reuses an
        # operator's previous snapshot file when the version is
        # unchanged — a mutated broadcast state must bump it)
        self.state_version = 0

    def process_main(self, ts: np.ndarray, data: Batch,
                     valid: np.ndarray) -> None:
        compact = {k: np.asarray(v)[valid] for k, v in data.items()}
        tsc = np.asarray(ts)[valid]
        out = self.fn.process_element(compact, tsc, self.state)
        if out:
            out = {k: np.asarray(v) for k, v in out.items()}
            lens = {len(v) for v in out.values()}
            if len(lens) > 1:
                raise ValueError(
                    f"process_element returned ragged columns: "
                    f"{ {k: len(v) for k, v in out.items()} }")
            n = lens.pop() if lens else 0
            if n:
                # downstream event time: the function may emit explicit
                # per-row __ts__; otherwise rows carry the batch's max
                # input timestamp (they happened 'by then')
                out.setdefault("__ts__", np.full(
                    n, int(tsc.max()) if len(tsc) else 0, np.int64))
                self._out.append(out)

    def process_broadcast(self, ts: np.ndarray, data: Batch,
                          valid: np.ndarray) -> None:
        compact = {k: np.asarray(v)[valid] for k, v in data.items()}
        self.fn.process_broadcast_element(
            compact, np.asarray(ts)[valid], self.state)
        self.state_version += 1

    def take_fired(self):
        """Rows emitted since the last take, wrapped as the lazy
        FiredWindows the drain thread expects."""
        from flink_tpu.ops.window import FiredWindows

        if not self._out:
            return None
        if len(self._out) == 1:
            out = self._out[0]
        else:
            out = {k: np.concatenate([b[k] for b in self._out])
                   for k in self._out[0]}
        self._out = []
        return FiredWindows(data=out)

    # -- checkpointing ---------------------------------------------------
    def snapshot_state(self) -> Dict[str, Any]:
        return {"broadcast_state": copy.deepcopy(self.state)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.state = copy.deepcopy(snap.get("broadcast_state", {}))
        self._out = []
