"""KeyedProcessFunction — general keyed state + user timers.

ref: streaming/api/functions/KeyedProcessFunction.java lowered through
streaming/api/operators/KeyedProcessOperator.java, with timers in
InternalTimerServiceImpl (a per-key-group heap of (key, namespace, ts),
polled on each watermark advance).

TPU-first redesign: the reference's contract is per-RECORD —
``processElement(value, ctx)`` with state probes and timer calls per
element. Here the contract is per-BATCH: ``process_batch(ctx)`` sees
the whole microbatch as struct-of-arrays plus a slot vector into
columnar state (state/api.py), so state access is one gather/scatter
per column instead of B hash probes, and timer registration is one
append of (slot, ts) pairs. The timer service itself is an array pair
sorted at fire time — firing every due timer is one mask + one user
callback over the due set (the vectorized analogue of the reference's
heap-poll loop). A per-record adapter (``api.functions
.KeyedProcessFunction.process_element``) recovers the reference's
element-at-a-time authoring style at host-loop speed for logic that
truly needs sequential per-record semantics.
"""
from __future__ import annotations

import collections
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.state.api import (
    ListStateDescriptor, ListStateVector, MapStateDescriptor,
    MapStateVector, ValueStateDescriptor, ValueStateVector)
from flink_tpu.state.keyed import KeyDirectory, account_full_drop
from flink_tpu.time.watermarks import LONG_MIN


class TimerService:
    """Vectorized event-time timer wheel (ref: InternalTimerServiceImpl).
    The consolidated set stays SORTED by (ts, slot) and deduplicated, so
    a watermark sweep is one binary search for the due boundary — the
    O(due) analogue of the reference's heap poll. New registrations
    accumulate in append buffers and merge in one sort on the next sweep
    (cost proportional to what changed, not to the pending-set size)."""

    def __init__(self) -> None:
        self._ts = np.zeros(0, np.int64)     # sorted, deduped with _slots
        self._slots = np.zeros(0, np.int64)
        self._pend_ts: List[np.ndarray] = []
        self._pend_slots: List[np.ndarray] = []
        self._del_ts: List[np.ndarray] = []
        self._del_slots: List[np.ndarray] = []

    def register_batch(self, slots: np.ndarray, ts: np.ndarray) -> None:
        """Register one event-time timer per (slot, ts) pair."""
        if len(slots):
            self._pend_slots.append(np.asarray(slots, np.int64).copy())
            self._pend_ts.append(np.asarray(ts, np.int64).copy())

    def delete_batch(self, slots: np.ndarray, ts: np.ndarray) -> None:
        if len(slots):
            self._del_slots.append(np.asarray(slots, np.int64).copy())
            self._del_ts.append(np.asarray(ts, np.int64).copy())

    @property
    def pending_count(self) -> int:
        return len(self._ts) + sum(len(a) for a in self._pend_ts)

    def max_pending_ts(self) -> Optional[int]:
        vals = [int(self._ts[-1])] if len(self._ts) else []
        vals += [int(a.max()) for a in self._pend_ts if len(a)]
        return max(vals) if vals else None

    def _consolidate(self) -> None:
        if self._pend_ts:
            ts = np.concatenate([self._ts] + self._pend_ts)
            slots = np.concatenate([self._slots] + self._pend_slots)
            order = np.lexsort((slots, ts))
            ts, slots = ts[order], slots[order]
            if len(ts):  # adjacent dedup (timer-SET semantics)
                keep = np.empty(len(ts), bool)
                keep[0] = True
                keep[1:] = (ts[1:] != ts[:-1]) | (slots[1:] != slots[:-1])
                ts, slots = ts[keep], slots[keep]
            self._ts, self._slots = ts, slots
            self._pend_ts, self._pend_slots = [], []
        if self._del_ts and len(self._ts):
            # few deletions against a sorted set: binary-search each
            dts = np.concatenate(self._del_ts)
            dsl = np.concatenate(self._del_slots)
            pos = np.searchsorted(self._ts, dts, "left")
            kill = np.zeros(len(self._ts), bool)
            for p, t, s in zip(pos.tolist(), dts.tolist(), dsl.tolist()):
                while p < len(self._ts) and self._ts[p] == t:
                    if self._slots[p] == s:
                        kill[p] = True
                        break
                    p += 1
            if kill.any():
                self._ts, self._slots = self._ts[~kill], self._slots[~kill]
        self._del_ts, self._del_slots = [], []

    def due(self, wm: int) -> Tuple[np.ndarray, np.ndarray]:
        """Pop every timer with ts <= wm, fire-ordered by (ts, slot)."""
        self._consolidate()
        cut = int(np.searchsorted(self._ts, wm, "right"))
        if cut == 0:
            return np.zeros(0, np.int64), np.zeros(0, np.int64)
        due_s, due_t = self._slots[:cut].copy(), self._ts[:cut].copy()
        self._ts, self._slots = self._ts[cut:], self._slots[cut:]
        return due_s, due_t

    def snapshot(self) -> Dict[str, Any]:
        self._consolidate()
        return {"slots": self._slots.copy(), "ts": self._ts.copy(),
                "deleted": []}

    def restore(self, snap: Dict[str, Any]) -> None:
        self._ts = np.array(snap["ts"])
        self._slots = np.array(snap["slots"])
        self._pend_ts, self._pend_slots = [], []
        self._del_ts, self._del_slots = [], []
        for s, t in snap.get("deleted", ()):  # legacy snapshots
            self.delete_batch(np.array([s]), np.array([t]))


class ProcessContext:
    """What the user function sees — batch-vectorized (ref: the
    Context/OnTimerContext pair of KeyedProcessFunction)."""

    def __init__(self, op: "KeyedProcessOperator") -> None:
        self._op = op
        # per-call fields (set by the operator before each invocation)
        self.keys: np.ndarray = np.zeros(0, np.int64)
        self.slots: np.ndarray = np.zeros(0, np.int64)
        self.timestamps: np.ndarray = np.zeros(0, np.int64)
        self.data: Dict[str, np.ndarray] = {}
        # which timer family invoked on_timer: "event" | "processing"
        # (ref: OnTimerContext.timeDomain())
        self.time_domain: str = "event"

    @property
    def watermark(self) -> int:
        return self._op.watermark

    # -- state -----------------------------------------------------------

    def value_state(self, desc: ValueStateDescriptor) -> ValueStateVector:
        return self._op._state(desc, ValueStateVector)

    def list_state(self, desc: ListStateDescriptor) -> ListStateVector:
        return self._op._state(desc, ListStateVector)

    def map_state(self, desc: MapStateDescriptor) -> MapStateVector:
        return self._op._state(desc, MapStateVector)

    # -- timers ----------------------------------------------------------

    def register_event_time_timers(self, ts: np.ndarray,
                                   slots: Optional[np.ndarray] = None) -> None:
        self._op.timers.register_batch(
            self.slots if slots is None else slots, np.asarray(ts))

    def delete_event_time_timers(self, ts: np.ndarray,
                                 slots: Optional[np.ndarray] = None) -> None:
        self._op.timers.delete_batch(
            self.slots if slots is None else slots, np.asarray(ts))

    def register_processing_time_timers(
            self, ts: np.ndarray,
            slots: Optional[np.ndarray] = None) -> None:
        """Per-key timers on the WALL clock (ref: TimerService.
        registerProcessingTimeTimer — the proc-time half of
        InternalTimerServiceImpl). Fired by the runtime's clock advance
        between steps; resolution is one microbatch."""
        self._op.proc_timers.register_batch(
            self.slots if slots is None else slots, np.asarray(ts))

    def delete_processing_time_timers(
            self, ts: np.ndarray,
            slots: Optional[np.ndarray] = None) -> None:
        self._op.proc_timers.delete_batch(
            self.slots if slots is None else slots, np.asarray(ts))

    def current_processing_time(self) -> int:
        return self._op.clock.now_ms()

    # -- output ----------------------------------------------------------

    def emit(self, rows: Dict[str, np.ndarray],
             ts: Optional[np.ndarray] = None) -> None:
        """Collect output rows (struct-of-arrays). Every emit within one
        drain window must use the SAME field set. ``ts`` may be omitted
        only for a full-batch emission (one row per input record, in
        order); any other shape must pass explicit per-row timestamps —
        silently stamping unrelated rows with the batch prefix's times
        would route them into the wrong downstream windows."""
        n = len(next(iter(rows.values()))) if rows else 0
        if ts is None:
            if n != len(self.timestamps):
                raise ValueError(
                    f"emit of {n} rows without ts: defaults only apply "
                    f"to full-batch emissions ({len(self.timestamps)} "
                    "records); pass ts= explicitly")
            out_ts = self.timestamps
        else:
            out_ts = np.asarray(ts, np.int64)
            if len(out_ts) != n:
                raise ValueError(
                    f"emit ts length {len(out_ts)} != rows length {n}")
        self._op._emitted.append(({k: np.asarray(v) for k, v in rows.items()},
                                  out_ts))


class KeyedProcessOperator:
    """Driver-facing operator for ``KeyedStream.process`` (ref:
    KeyedProcessOperator). The user function gets batch-vectorized
    ``process_batch(ctx)`` and ``on_timer(ctx)`` hooks."""

    def __init__(self, fn: Any, *, num_shards: int = 128,
                 slots_per_shard: int = 1024) -> None:
        from flink_tpu.time.clock import SystemProcessingTimeService

        self.fn = fn
        self.directory = KeyDirectory(num_shards, slots_per_shard)
        self.capacity = num_shards * slots_per_shard
        self.timers = TimerService()
        self.proc_timers = TimerService()
        self.clock = SystemProcessingTimeService()
        self.watermark = LONG_MIN
        self.late_records = 0
        self.records_dropped_full = 0
        self.state_version = 0
        self._states: Dict[str, Any] = {}
        self._descs: Dict[str, Any] = {}
        self._emitted: collections.deque = collections.deque()
        self.ctx = ProcessContext(self)

    def _state(self, desc, cls):
        st = self._states.get(desc.name)
        if st is None:
            st = cls(desc, self.capacity)
            self._states[desc.name] = st
            self._descs[desc.name] = desc
        elif not isinstance(st, cls):
            raise TypeError(
                f"state '{desc.name}' already registered as "
                f"{type(st).__name__}")
        return st

    # -- data plane ------------------------------------------------------

    def process_batch(self, keys, ts, data: Dict[str, np.ndarray],
                      valid=None) -> None:
        self.state_version += 1
        keys = np.asarray(keys, np.int64)
        ts = np.asarray(ts, np.int64)
        valid = (np.ones(len(ts), bool) if valid is None
                 else np.asarray(valid, bool))
        # assign slots for VALID rows only — filtered-out records must
        # not consume directory capacity for the life of the job
        idx = np.nonzero(valid)[0]
        if len(idx) == 0:
            return
        slots = self.directory.assign(keys[idx])
        bad = slots < 0
        if bad.any():
            account_full_drop(self, int(bad.sum()))
            idx = idx[~bad]
            slots = slots[~bad]
        if len(idx) == 0:
            return
        ctx = self.ctx
        ctx.keys = keys[idx]
        ctx.slots = slots.astype(np.int64)
        ctx.timestamps = ts[idx]
        ctx.data = {k: np.asarray(v)[idx] for k, v in data.items()}
        self.fn.process_batch(ctx)

    # -- time plane ------------------------------------------------------

    def advance_watermark(self, wm: int):
        from flink_tpu.ops.window import FiredWindows

        if wm > self.watermark:
            self.watermark = wm
            due_slots, due_ts = self.timers.due(wm)
            if len(due_slots):
                self.state_version += 1
                ctx = self.ctx
                ctx.slots = due_slots
                ctx.keys = self.directory.key_of_slots(due_slots)
                ctx.timestamps = due_ts
                ctx.data = {}
                ctx.time_domain = "event"
                self.fn.on_timer(ctx)
        return FiredWindows(data=self._drain_emitted())

    def advance_processing_time_timers(self, fire_all: bool = False):
        """Fire processing-time timers the clock has passed (the
        proc-time half of InternalTimerServiceImpl.advanceWatermark;
        driven by the runtime between steps). ``fire_all`` implements
        drain semantics at end of input. Returns a FiredWindows batch
        or None when nothing fired."""
        from flink_tpu.ops.window import FiredWindows

        horizon = (np.iinfo(np.int64).max - 1 if fire_all
                   else self.clock.now_ms())
        due_slots, due_ts = self.proc_timers.due(horizon)
        if not len(due_slots):
            return None
        self.state_version += 1
        ctx = self.ctx
        ctx.slots = due_slots
        ctx.keys = self.directory.key_of_slots(due_slots)
        ctx.timestamps = due_ts
        ctx.data = {}
        ctx.time_domain = "processing"
        self.fn.on_timer(ctx)
        return FiredWindows(data=self._drain_emitted())

    def take_fired(self):
        """Rows emitted by process_batch calls since the last take (the
        driver forwards them immediately, like count-window fires)."""
        from flink_tpu.ops.window import FiredWindows

        if not self._emitted:
            return None
        return FiredWindows(data=self._drain_emitted())

    def _drain_emitted(self) -> Dict[str, np.ndarray]:
        if not self._emitted:
            return {"__ts__": np.zeros(0, np.int64)}
        parts = list(self._emitted)
        self._emitted.clear()
        fields = set(parts[0][0])
        for p in parts[1:]:
            if set(p[0]) != fields:
                raise ValueError(
                    "ctx.emit calls in one drain window used differing "
                    f"schemas: {sorted(fields)} vs {sorted(p[0])}")
        out = {k: np.concatenate([p[0][k] for p in parts]) for k in fields}
        out["__ts__"] = np.concatenate([p[1] for p in parts])
        return out

    def final_watermark(self) -> int:
        # fire every remaining registered timer at end of input (the
        # reference advances to MAX_WATERMARK)
        mx = self.timers.max_pending_ts()
        if mx is not None:
            return max(mx, self.watermark)
        return self.watermark if self.watermark != LONG_MIN else 0

    def quiesce(self) -> None:
        pass

    def throttle(self) -> None:
        pass

    # -- snapshot seam ----------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "kind": "process",
            "directory": self.directory.snapshot(),
            "timers": self.timers.snapshot(),
            "proc_timers": self.proc_timers.snapshot(),
            "watermark": self.watermark,
            "late_records": self.late_records,
            "records_dropped_full": self.records_dropped_full,
            "states": {n: (type(s).__name__, self._descs[n], s.snapshot())
                       for n, s in self._states.items()},
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        import flink_tpu.state.api as state_api

        self.directory = KeyDirectory.restore(
            self.directory.num_shards, self.directory.slots_per_shard,
            snap["directory"],
            (self.directory.shard_lo, self.directory.shard_hi))
        self.timers.restore(snap["timers"])
        if snap.get("proc_timers") is not None:
            self.proc_timers.restore(snap["proc_timers"])
        self.watermark = snap["watermark"]
        self.late_records = snap["late_records"]
        self.records_dropped_full = snap["records_dropped_full"]
        self._states = {}
        self._descs = {}
        for name, (cls_name, desc, st_snap) in snap["states"].items():
            cls = getattr(state_api, cls_name)
            st = cls(desc, self.capacity)
            st.restore(st_snap)
            self._states[name] = st
            self._descs[name] = desc
        self._emitted.clear()
