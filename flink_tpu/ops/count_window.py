"""Count windows: GlobalWindows + Count/Purging trigger, lowered TPU-first.

The reference implements ``countWindow(n)`` as GlobalWindows +
PurgingTrigger(CountTrigger(n)) — a per-(key, window) trigger count in
partitioned state, checked on EVERY element (ref: streaming/api/
datastream/KeyedStream.java countWindow, triggers/CountTrigger.java,
assigners/GlobalWindows.java). A per-element host check is the opposite
of what a TPU wants; here the whole microbatch folds into per-key lane
state with three scatters, and the trigger is a VECTORIZED mask over
the since-last-fire count lane evaluated once per step, on device.
Fired rows compact into a packed buffer (count header + rows, the same
single-transfer shape as the time-window fire path).

Semantics (documented batching tradeoff, same contract as
CountTrigger's docstring): trigger evaluation happens at microbatch
boundaries, so a key crossing N within one batch fires ONCE with its
full accumulated aggregate instead of once per N. Fires are therefore
deterministic given the batching, and exactly the reference's when
batch size is 1. As in the reference, GlobalWindows never fires on
event time — keys holding fewer than N elements at end-of-input emit
nothing.
"""
from __future__ import annotations

import collections
import functools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from flink_tpu.ops.aggregates import LaneAggregate
from flink_tpu.ops.window import FiredWindows, _next_pow2
from flink_tpu.state.keyed import KeyDirectory, account_full_drop
from flink_tpu.time.watermarks import LONG_MIN

# GlobalWindow.maxTimestamp() analogue — a finite sentinel end for the
# eternal window (ref: windowing/windows/GlobalWindow.java)
GLOBAL_WINDOW_END = np.int64(1) << 62

_NEG_INF = float("-inf")
_POS_INF = float("inf")


class CountWindowOperator:
    """Keyed count-window aggregation (fires every ``size`` elements).

    ``purge=True`` is ``countWindow(n)`` (PurgingTrigger: window state
    resets at fire); ``purge=False`` is a bare CountTrigger on
    GlobalWindows (state keeps accumulating, only the trigger count
    resets — ref: CountTrigger.onElement clears its ReducingState but
    not the window contents).
    """

    def __init__(
        self,
        agg: LaneAggregate,
        size: int,
        *,
        purge: bool = True,
        num_shards: int = 128,
        slots_per_shard: int = 1024,
    ) -> None:
        if size < 1:
            raise ValueError(f"count window size must be >= 1, got {size}")
        self.agg = agg
        self.size = size
        self.purge = purge
        self.directory = KeyDirectory(num_shards, slots_per_shard)
        self.R = num_shards * slots_per_shard
        R1 = self.R + 1  # + dump row for invalid records
        self.state = (
            jnp.zeros((R1, agg.sum_width), jnp.float32),
            jnp.full((R1, agg.max_width), _NEG_INF, jnp.float32),
            jnp.full((R1, agg.min_width), _POS_INF, jnp.float32),
            jnp.zeros((R1,), jnp.int32),   # total count (finalize input)
            jnp.zeros((R1,), jnp.int32),   # since-last-fire (trigger)
        )
        self.watermark = LONG_MIN
        self.late_records = 0
        self.records_dropped_full = 0
        self.state_version = 0
        self._pending: collections.deque = collections.deque()
        res = agg.finalize(
            np.zeros((0, agg.sum_width), np.float32),
            np.zeros((0, agg.max_width), np.float32),
            np.zeros((0, agg.min_width), np.float32),
            np.zeros((0,), np.int32))
        self._res_fields = sorted(res)
        self._res_is_int = {
            k: np.issubdtype(np.asarray(res[k]).dtype, np.integer)
            for k in res}
        self._step = self._build_step()
        self._empty_cache: Optional[Dict[str, np.ndarray]] = None

    # -- device step -----------------------------------------------------

    def _build_step(self):
        agg, R, N, purge = self.agg, self.R, self.size, self.purge
        fields = self._res_fields
        is_int = self._res_is_int

        @functools.partial(jax.jit, donate_argnums=(0,))
        def step(state, slots, valid, data):
            sums, maxs, mins, counts, since = state
            s_l, x_l, n_l = agg.lift_masked(data, valid)
            sums = sums.at[slots].add(s_l)
            maxs = maxs.at[slots].max(x_l)
            mins = mins.at[slots].min(n_l)
            inc = valid.astype(jnp.int32)
            counts = counts.at[slots].add(inc)
            since = since.at[slots].add(inc)
            fired = jnp.arange(R + 1) < R
            fired = fired & (since >= N)
            # finalize every row (cheap: R rows, fully vectorized on
            # device), then compact the fired ones into a packed buffer
            res = agg.finalize(sums, maxs, mins, counts)
            cols = [jnp.arange(R + 1, dtype=jnp.int32), counts]
            for f in fields:
                v = res[f]
                cols.append(v.astype(jnp.int32) if is_int[f]
                            else lax.bitcast_convert_type(
                                v.astype(jnp.float32), jnp.int32))
            mat = jnp.stack(cols, axis=1)
            pos = jnp.cumsum(fired.astype(jnp.int32))
            idx = jnp.where(fired, pos, R + 1)          # dump to last row
            buf = jnp.zeros((R + 2, mat.shape[1]), jnp.int32)
            buf = buf.at[0, 0].set(pos[-1])
            buf = buf.at[idx].set(mat)
            if purge:
                f2 = fired[:, None]
                sums = jnp.where(f2, 0.0, sums)
                maxs = jnp.where(f2, _NEG_INF, maxs)
                mins = jnp.where(f2, _POS_INF, mins)
                counts = jnp.where(fired, 0, counts)
            since = jnp.where(fired, 0, since)
            return (sums, maxs, mins, counts, since), buf

        return step

    # -- data plane ------------------------------------------------------

    def process_batch(
        self,
        keys: np.ndarray,
        ts: np.ndarray,
        data: Dict[str, np.ndarray],
        valid: Optional[np.ndarray] = None,
    ) -> None:
        self.state_version += 1
        keys = np.asarray(keys, dtype=np.int64)
        b = len(keys)
        valid = np.ones(b, bool) if valid is None else np.asarray(valid, bool)
        slots = self.directory.assign(keys)
        bad = valid & (slots < 0)
        if bad.any():
            account_full_drop(self, int(bad.sum()))
            valid = valid & ~bad
        slots = np.where(valid, slots, self.R).astype(np.int32)
        if self.agg.fields is not None:
            data = {k: data[k] for k in self.agg.fields}
        # pow2-bucket the batch so each size compiles once
        target = _next_pow2(max(b, 1))
        if target != b:
            pad = target - b
            slots = np.concatenate([slots, np.full(pad, self.R, np.int32)])
            valid = np.concatenate([valid, np.zeros(pad, bool)])
            data = {k: np.concatenate(
                [np.asarray(v),
                 np.zeros((pad,) + np.asarray(v).shape[1:],
                          np.asarray(v).dtype)]) for k, v in data.items()}
        self.state, buf = self._step(
            self.state, jnp.asarray(slots), jnp.asarray(valid),
            {k: jnp.asarray(v) for k, v in data.items()})
        buf.copy_to_host_async()
        self._pending.append(buf)

    def take_fired(self) -> Optional[FiredWindows]:
        """The fires produced by the batches pushed since the last take,
        as a lazy FiredWindows (the driver emits this right after
        process_batch — count fires are per-step, not per-watermark)."""
        if not self._pending:
            return None
        bufs = list(self._pending)
        self._pending.clear()
        return FiredWindows(fetch=lambda: self._decode(bufs))

    def _decode(self, bufs: List[jax.Array]) -> Dict[str, np.ndarray]:
        segs = []
        for buf in bufs:
            arr = np.asarray(buf)
            n = int(arr[0, 0])
            if n:
                segs.append(arr[1:1 + n])
        if segs:
            body = np.concatenate(segs)
        else:
            body = np.zeros((0, 2 + len(self._res_fields)), np.int32)
        nrec = len(body)
        out: Dict[str, np.ndarray] = {
            "key": self.directory.key_of_slots(body[:, 0].astype(np.int64)),
            "window_start": np.zeros(nrec, np.int64),
            "window_end": np.full(nrec, GLOBAL_WINDOW_END, np.int64),
            "count": body[:, 1],
        }
        for i, f in enumerate(self._res_fields):
            if f == "count":
                continue
            col = np.ascontiguousarray(body[:, 2 + i])
            out[f] = col if self._res_is_int[f] else col.view(np.float32)
        return out

    # -- time plane (count windows are event-time-blind) -----------------

    def advance_watermark(self, wm: int) -> FiredWindows:
        if wm > self.watermark:
            self.watermark = wm
            self.state_version += 1  # snapshotted field changed
        if self._empty_cache is None:
            from flink_tpu.ops.window import _empty_fired
            self._empty_cache = _empty_fired(self.agg)
        return FiredWindows(data=dict(self._empty_cache))

    def final_watermark(self) -> int:
        # GlobalWindows never completes: no end-of-input flush (ref:
        # GlobalWindows' default NeverTrigger behavior for non-count
        # firing) — partial groups emit nothing, like the reference
        return self.watermark

    def quiesce(self) -> None:
        from flink_tpu.hostsync import ready_wait
        ready_wait(self.state[3])

    def throttle(self) -> None:  # driver-loop protocol compatibility
        pass

    # -- snapshot seam ----------------------------------------------------

    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "kind": "count_window",
            # on-device clone (not a fetch): the checkpoint executor
            # materializes off the hot loop; clone because the next step
            # donates self.state's buffers
            "arrays": tuple(jnp.array(a, copy=True) for a in self.state),
            "directory": self.directory.snapshot(),
            "watermark": self.watermark,
            "late_records": self.late_records,
            "records_dropped_full": self.records_dropped_full,
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.state = tuple(jnp.asarray(a) for a in snap["arrays"])
        self.directory = KeyDirectory.restore(
            self.directory.num_shards, self.directory.slots_per_shard,
            snap["directory"],
            (self.directory.shard_lo, self.directory.shard_hi))
        self.watermark = snap["watermark"]
        self.late_records = snap["late_records"]
        self.records_dropped_full = snap.get("records_dropped_full", 0)
        self._pending.clear()
