"""Session windows — gap-merged, dynamically-bounded windows.

ref: streaming/api/windowing/assigners/EventTimeSessionWindows.java and
the merge machinery MergingWindowSet.java + WindowOperator's merging
branch (each element opens [ts, ts+gap) and overlapping windows merge,
state merges via namespace re-targeting).

TPU-first redesign (SURVEY §8.4 item 3): dynamic merging cannot be a
static pane layout, so the decomposition is:
- **batch sessionization is vectorized**: sort the microbatch by
  (key, ts); session boundaries are where the key changes or the time
  gap exceeds ``gap``; per-batch-session aggregates come from numpy
  ``reduceat`` segments (C-speed host work — the per-RECORD cost is
  vectorized away, matching how the reference's cost is per element).
- the **span registry is COLUMNAR** (struct-of-arrays sorted by
  (key, start), one row per open/retained session — the
  MergingWindowSet role at fleet scale): batch segments merge into it
  with one lexsort + an offset-encoded interval-union scan + reduceat
  combines. No per-key Python objects, no per-span loops — a 1M-key
  churn batch costs a few array passes (the round-2 registry held a
  Python list of dataclasses per key and died at exactly that scale).
- fired sessions stay in the registry until allowed lateness expires so
  late records re-open/merge and re-fire (late firing semantics).
- the registry is **key-sharded onto the host pool** (PROFILE.md §9.1):
  under ``host.parallelism = W > 1`` it splits into W independent span
  stores (``key % W`` — the key-group discipline), and the per-shard
  merge/fire/expiry passes run as pool tasks. Sessions never merge
  across keys, so no cross-shard invariant exists; fired shards'
  rows re-sort by (key, start) so output bytes match the serial path
  exactly (the §9 determinism contract). ``host.parallelism = 1`` IS
  the serial path: one store, no partitioning, no pool threads.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.ops.aggregates import LaneAggregate
from flink_tpu.records import (
    OP_DTYPE,
    OP_FIELD,
    OP_INSERT,
    OP_UPDATE_AFTER,
    OP_UPDATE_BEFORE,
)
from flink_tpu.time.watermarks import LONG_MIN


class _SpanStore:
    """Columnar open/retained-session registry, sorted by (key, start).

    Invariant: per key, spans are disjoint and separated by more than
    ``gap`` (anything closer would have merged), so two REGISTRY spans
    can only merge when a new batch segment bridges them.
    """

    def __init__(self, sum_w: int, max_w: int, min_w: int) -> None:
        self.key = np.zeros(0, np.int64)
        self.start = np.zeros(0, np.int64)
        self.last = np.zeros(0, np.int64)   # max event ts; end = last+gap
        self.sums = np.zeros((0, sum_w), np.float32)
        self.maxs = np.zeros((0, max_w), np.float32)
        self.mins = np.zeros((0, min_w), np.float32)
        self.count = np.zeros(0, np.int64)
        self.fired = np.zeros(0, bool)
        self.refire = np.zeros(0, bool)
        # retract mode: True after a -U was emitted for a consumed
        # predecessor — the span's next fire is +U, not +I
        self.retracted = np.zeros(0, bool)

    def __len__(self) -> int:
        return len(self.key)

    _COLS = ("key", "start", "last", "sums", "maxs", "mins", "count",
             "fired", "refire", "retracted")

    def _take(self, idx) -> Tuple[np.ndarray, ...]:
        return tuple(getattr(self, c)[idx] for c in self._COLS)

    def _filter(self, keep: np.ndarray) -> None:
        for c in self._COLS:
            setattr(self, c, getattr(self, c)[keep])

    def ranges_for(self, uk: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """[lo, hi) row ranges of (sorted, unique) keys ``uk``."""
        return (np.searchsorted(self.key, uk, "left"),
                np.searchsorted(self.key, uk, "right"))

    def rows_for(self, uk: np.ndarray) -> np.ndarray:
        """All row indices whose key is in ``uk`` (sorted unique)."""
        lo, hi = self.ranges_for(uk)
        lens = hi - lo
        total = int(lens.sum())
        if total == 0:
            return np.zeros(0, np.int64)
        # concatenated aranges: repeat each lo, add a per-range arange
        reps = np.repeat(lo - np.concatenate(([0], np.cumsum(lens)[:-1])),
                         lens)
        return reps + np.arange(total)

    def insert_sorted(self, cols: Tuple[np.ndarray, ...]) -> None:
        """Insert merge results, keeping (key, start) order — one
        searchsorted + np.insert per column. The store may still hold a
        key's COLD prefix (spans the merge's participation cut passed
        through); every inserted span of that key starts later than its
        cold spans, so inserting at the key block's RIGHT edge preserves
        within-key start order."""
        pos = np.searchsorted(self.key, cols[0], side="right")
        n_old, n_new = len(self.key), len(cols[0])
        # manual two-way merge: compute the destination mask ONCE and
        # fancy-assign each column (np.insert re-derives it per call —
        # measured ~20ms/batch across the 9 columns)
        new_at = pos + np.arange(n_new)
        old_mask = np.ones(n_old + n_new, bool)
        old_mask[new_at] = False
        for c, new in zip(self._COLS, cols):
            cur = getattr(self, c)
            out = np.empty((n_old + n_new,) + cur.shape[1:], cur.dtype)
            out[old_mask] = cur
            out[new_at] = new
            setattr(self, c, out)


class SessionOperator:
    """Keyed event-time session aggregation with allowed lateness."""

    def __init__(
        self,
        gap_ms: int,
        agg: LaneAggregate,
        *,
        allowed_lateness_ms: int = 0,
        num_shards: int = 128,
        slots_per_shard: int = 1024,
        max_out_of_orderness_ms: int = 0,
        host_pool: Optional[Any] = None,
        retract: bool = False,
    ) -> None:
        if gap_ms <= 0:
            raise ValueError("session gap must be positive")
        self.gap = int(gap_ms)
        self.agg = agg
        self.retract = bool(retract)
        # retract rows produced by merges this step, drained by
        # take_fired immediately after each process_batch — the buffer
        # is always empty at checkpoint boundaries (snapshots happen
        # between steps, after emission), so it carries no state
        self._pending_retracts: List[Dict[str, np.ndarray]] = []
        self.lateness = int(allowed_lateness_ms)
        self.watermark = LONG_MIN
        self.late_records = 0
        self.state_version = 0
        # key-sharded registry (PROFILE §9.1): W independent stores at
        # host.parallelism = W; exactly one (the serial path) at W = 1
        self._pool = (host_pool if host_pool is not None
                      and host_pool.parallelism > 1 else None)
        n_shards = self._pool.parallelism if self._pool is not None else 1
        self._shards: List[_SpanStore] = [
            _SpanStore(agg.sum_width, agg.max_width, agg.min_width)
            for _ in range(n_shards)]
        self._has_refire = False

    # -- ingest ----------------------------------------------------------
    def process_batch(self, keys, ts, data: Dict[str, np.ndarray], valid=None) -> None:
        self.state_version += 1
        keys = np.asarray(keys, np.int64)
        ts = np.asarray(ts, np.int64)
        valid = np.ones(len(ts), bool) if valid is None else np.asarray(valid, bool)
        if self._pool is None:
            late, refire, retr = self._process_shard(
                self._shards[0], keys, ts, data, valid)
            self.late_records += late
            self._has_refire = self._has_refire or refire
            if retr is not None:
                self._pending_retracts.append(retr)
            return
        # partition by key shard; per-key work is identical to serial
        # (no session logic crosses keys), so per-shard passes compose
        # to the exact serial result
        n_shards = len(self._shards)
        shard = keys % n_shards
        data = {k: np.asarray(v) for k, v in data.items()}
        tasks = []
        for w in range(n_shards):
            m = shard == w
            if not bool(m.any()):
                continue
            tasks.append(lambda st=self._shards[w], m=m: self._process_shard(
                st, keys[m], ts[m],
                {k: v[m] for k, v in data.items()}, valid[m]))
        results = self._pool.run_tasks(tasks)
        self.late_records += sum(late for late, _, _ in results)
        self._has_refire = self._has_refire or any(
            refire for _, refire, _ in results)
        self._pending_retracts.extend(
            retr for _, _, retr in results if retr is not None)

    def _process_shard(self, st: _SpanStore, keys, ts,
                       data: Dict[str, np.ndarray], valid
                       ) -> Tuple[int, bool, Optional[Dict[str, np.ndarray]]]:
        """Full ingest pass for one shard's records against its store;
        returns (beyond-lateness drop count, refire-pending flag,
        retract rows from consumed fired spans or None). At
        host.parallelism=1 this IS the whole batch — the serial path.
        The results ride the return value rather than being written to
        ``self`` so pool-shard passes never touch shared state; the
        caller folds the per-shard results on its own thread."""
        late_count = 0
        # drop beyond-lateness records (side output accounting): a record
        # is late iff its singleton session is dead AND it cannot merge
        # into any retained span (the reference checks isWindowLate on
        # the POST-merge window — a record touching a live retained
        # session rides that session's lateness)
        if self.watermark != LONG_MIN:
            late = valid & (ts + self.gap - 1 + self.lateness <= self.watermark)
            cand = np.nonzero(late)[0]
            if len(cand):
                # Vectorized merge-rescue check (was a per-candidate
                # Python loop — tens of ms per batch at 2% lateness):
                # per key, spans are disjoint and > gap apart, so the
                # ONLY span a record t can merge with is the rightmost
                # one with start <= t + gap — one searchsorted over the
                # candidate keys' span subset finds it.
                uk = np.unique(keys[cand])
                rows = st.rows_for(uk)
                if len(rows):
                    sk_sub = st.key[rows]
                    ss_sub = st.start[rows]
                    sl_sub = st.last[rows]
                    tmin = int(ss_sub.min())
                    span = int(ss_sub.max()) - tmin + 2
                    if (len(uk) + 1) * span < 2**62:
                        krank = np.searchsorted(uk, sk_sub).astype(np.int64)
                        enc = krank * span + (ss_sub - tmin)
                        ck = np.searchsorted(uk, keys[cand]).astype(np.int64)
                        q = ck * span + np.clip(
                            ts[cand] + self.gap - tmin, 0, span - 1)
                        pos = np.searchsorted(enc, q, "right") - 1
                        posc = np.clip(pos, 0, len(rows) - 1)
                        ok = ((pos >= 0) & (krank[posc] == ck)
                              & (ts[cand] <= sl_sub[posc] + self.gap)
                              & (ss_sub[posc] <= ts[cand] + self.gap))
                        late[cand[ok]] = False
                    else:  # pathological time range (same guard as the
                        # merge's encoding): per-candidate check
                        lo, hi = st.ranges_for(uk)
                        p = np.searchsorted(uk, keys[cand])
                        for j, i in enumerate(cand):
                            a, b = lo[p[j]], hi[p[j]]
                            t = ts[i]
                            if a < b and bool(np.any(
                                    (st.start[a:b] <= t + self.gap)
                                    & (t <= st.last[a:b] + self.gap))):
                                late[i] = False
            late_count = int(late.sum())
            valid = valid & ~late
        if not valid.any():
            return late_count, False, None
        keys = keys[valid]
        ts = ts[valid]
        data = {k: np.asarray(v)[valid] for k, v in data.items()}

        # vectorized batch sessionization: sort by (key, ts) — an
        # encoded single-key argsort (key band + in-batch ts offset)
        # beats np.lexsort ~3x at this size
        tmin = int(ts.min())
        tspan = int(ts.max()) - tmin + 1
        if int(np.abs(keys).max()) < (2**62) // max(tspan, 1):
            enc = keys * tspan + (ts - tmin)
            if data:
                order = np.argsort(enc, kind="stable")
                sk, st_ = keys[order], ts[order]
            else:
                es = np.sort(enc)
                sk, st_ = es // tspan, es % tspan + tmin
                order = None
        else:  # astronomically wide key domain: fall back
            order = np.lexsort((ts, keys))
            sk, st_ = keys[order], ts[order]
        sdata = ({k: v[order] for k, v in data.items()}
                 if data else {})
        new_seg = np.empty(len(sk), bool)
        new_seg[0] = True
        new_seg[1:] = (sk[1:] != sk[:-1]) | (st_[1:] - st_[:-1] > self.gap)
        seg_starts = np.nonzero(new_seg)[0]

        # per-segment lane aggregates (host lift on CPU jax → numpy)
        s_l, mx_l, mn_l = self._host_lift(sdata, np.ones(len(sk), bool))
        G = len(seg_starts)
        seg_sum = (np.add.reduceat(s_l, seg_starts, axis=0)
                   if s_l.shape[1] else np.zeros((G, 0), np.float32))
        seg_max = (np.maximum.reduceat(mx_l, seg_starts, axis=0)
                   if mx_l.shape[1] else np.zeros((G, 0), np.float32))
        seg_min = (np.minimum.reduceat(mn_l, seg_starts, axis=0)
                   if mn_l.shape[1] else np.zeros((G, 0), np.float32))
        seg_ends = np.append(seg_starts[1:], len(sk))
        refire, retr = self._merge_segments(
            st, sk[seg_starts], st_[seg_starts], st_[seg_ends - 1],
            seg_sum, seg_max, seg_min,
            (seg_ends - seg_starts).astype(np.int64))
        return late_count, refire, retr

    def _host_lift(self, data, valid) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run the aggregate's lift on the host CPU backend (session lane
        math is per-batch-segment, tiny — shipping it to the accelerator
        would cost a round trip per batch)."""
        import jax

        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            import jax.numpy as jnp

            s, mx, mn = self.agg.lift_masked(
                {k: jnp.asarray(v) for k, v in data.items()}, jnp.asarray(valid))
            return np.asarray(s), np.asarray(mx), np.asarray(mn)

    def _merge_segments(self, st: _SpanStore, seg_key, seg_tmin, seg_tmax,
                        seg_sum, seg_max, seg_min, seg_count
                        ) -> Tuple[bool, Optional[Dict[str, np.ndarray]]]:
        """Merge batch segments into shard registry ``st`` — the
        MergingWindowSet role, fully vectorized: pull every touched
        key's spans, run one interval-union scan over (touched ∪ new)
        sorted by (key, start), combine groups with reduceat, splice
        the results back. In retract mode, returns a -U row for every
        FIRED registry span a merge consumes: its emitted (key,
        window_start, window_end, aggregates) row is now stale and the
        accumulators still hold exactly the values it fired with (a
        fired span only changes by being consumed, which destroys it)."""
        gap = self.gap
        uk, first = np.unique(seg_key, return_index=True)
        touched_idx = st.rows_for(uk)
        if len(touched_idx):
            # participation cut: a registry span whose chain end
            # (last + gap) precedes its key's OLDEST new segment can
            # neither merge with nor be bridged to anything in this
            # batch (registry spans of one key are already > gap
            # apart), so it passes through untouched. Under lateness
            # retention most of a key's spans are such cold history —
            # pulling them through the merge was most of its cost.
            key_min = seg_tmin[first]  # segments are (key, ts)-sorted
            kr = np.searchsorted(uk, st.key[touched_idx])
            touched_idx = touched_idx[
                st.last[touched_idx] + gap >= key_min[kr]]
        (tk, tstart, tlast, tsum, tmax, tmin, tcount, tfired,
         trefire, tretr) = st._take(touched_idx)
        if len(touched_idx):
            keep = np.ones(len(st), bool)
            keep[touched_idx] = False
            st._filter(keep)

        n_t = len(tk)
        all_key = np.concatenate([tk, seg_key])
        all_start = np.concatenate([tstart, seg_tmin])
        all_last = np.concatenate([tlast, seg_tmax])
        all_sum = np.concatenate([tsum, seg_sum])
        all_max = np.concatenate([tmax, seg_max])
        all_min = np.concatenate([tmin, seg_min])
        all_count = np.concatenate([tcount, seg_count])
        all_fired = np.concatenate([tfired, np.zeros(len(seg_key), bool)])
        all_refire = np.concatenate([trefire, np.zeros(len(seg_key), bool)])
        all_retr = np.concatenate([tretr, np.zeros(len(seg_key), bool)])
        is_new = np.concatenate(
            [np.zeros(n_t, bool), np.ones(len(seg_key), bool)])

        order = np.lexsort((all_start, all_key))
        k_o = all_key[order]
        s_o = all_start[order]
        l_o = all_last[order]

        # interval-union scan with offset encoding: give each key's
        # timeline its own disjoint numeric band so ONE global
        # maximum.accumulate implements the per-key running chain-end
        # (merge iff start <= chain_last + gap)
        base = int(s_o.min())
        span = int(l_o.max()) + gap - base + 2
        krank = np.searchsorted(uk, k_o).astype(np.int64)
        if (len(uk) + 1) * span < 2**62:
            enc_start = krank * span + (s_o - base)
            enc_chain = krank * span + (l_o - base) + gap
            cm = np.maximum.accumulate(enc_chain)
            grp = np.empty(len(order), bool)
            grp[0] = True
            grp[1:] = enc_start[1:] > cm[:-1]
        else:  # pathological time range: per-key reset scan (rare)
            grp = np.empty(len(order), bool)
            grp[0] = True
            chain = l_o[0]
            for i in range(1, len(order)):
                if k_o[i] != k_o[i - 1] or s_o[i] > chain + gap:
                    grp[i] = True
                    chain = l_o[i]
                else:
                    grp[i] = False
                    chain = max(chain, l_o[i])

        gs = np.nonzero(grp)[0]
        m_key = k_o[gs]
        m_start = s_o[gs]  # group min: sorted by start within key
        m_last = np.maximum.reduceat(l_o, gs)
        m_sum = (np.add.reduceat(all_sum[order], gs, axis=0)
                 if all_sum.shape[1] else np.zeros((len(gs), 0), np.float32))
        m_max = (np.maximum.reduceat(all_max[order], gs, axis=0)
                 if all_max.shape[1] else np.zeros((len(gs), 0), np.float32))
        m_min = (np.minimum.reduceat(all_min[order], gs, axis=0)
                 if all_min.shape[1] else np.zeros((len(gs), 0), np.float32))
        m_count = np.add.reduceat(all_count[order], gs)
        fired_any = np.logical_or.reduceat(all_fired[order], gs)
        refire_any = np.logical_or.reduceat(all_refire[order], gs)
        retr_any = np.logical_or.reduceat(all_retr[order], gs)
        new_any = np.logical_or.reduceat(is_new[order], gs)
        size1 = np.append(gs[1:], len(order)) - gs == 1

        # untouched singleton registry spans pass through unchanged; any
        # group absorbing new content resets fired and inherits refire:
        # a late merge into a FIRED span, or a segment already complete
        # at the current watermark, (re-)fires at the next advance
        complete_now = (self.watermark != LONG_MIN) & (
            m_last + gap - 1 <= self.watermark)
        passthrough = size1 & ~new_any
        m_fired = np.where(passthrough, fired_any, False)
        m_refire = np.where(passthrough, refire_any,
                            fired_any | refire_any | complete_now)
        # a merged span whose constituents emitted (and now retract) a
        # row, or that inherited a still-pending retraction, (re)fires
        # as +U rather than +I
        m_retr = np.where(passthrough, retr_any, fired_any | retr_any)
        retract_rows = None
        if self.retract:
            # -U one row per consumed FIRED registry span (member-level
            # mask: registry member, fired, in a non-passthrough group)
            grp_sizes = np.append(gs[1:], len(order)) - gs
            pass_m = np.repeat(passthrough, grp_sizes)
            rm = ~is_new[order] & all_fired[order] & ~pass_m
            if rm.any():
                retract_rows = self._emit((
                    k_o[rm], s_o[rm], l_o[rm], all_sum[order][rm],
                    all_max[order][rm], all_min[order][rm],
                    all_count[order][rm]))
                retract_rows[OP_FIELD] = np.full(
                    int(rm.sum()), OP_UPDATE_BEFORE, OP_DTYPE)
        st.insert_sorted((m_key, m_start, m_last, m_sum, m_max, m_min,
                          m_count, m_fired, m_refire, m_retr))
        return bool(m_refire.any()), retract_rows

    # -- time ------------------------------------------------------------
    def advance_watermark(self, wm: int):
        from flink_tpu.ops.window import FiredWindows

        if wm < self.watermark and not self._has_refire:
            return FiredWindows(data=self._empty())
        self.state_version += 1
        self.watermark = max(self.watermark, wm)
        self._has_refire = False
        if self._pool is None:
            rows = self._advance_shard(self._shards[0])
        else:
            # per-shard fire/expiry on the pool; shard rows re-sort by
            # (key, start) — the serial store's emit order — so output
            # bytes are independent of the shard count
            parts = [r for r in self._pool.run_tasks(
                [lambda st=st: self._advance_shard(st)
                 for st in self._shards]) if r is not None]
            if not parts:
                rows = None
            elif len(parts) == 1:
                rows = parts[0]
            else:
                cat = {k: np.concatenate([p[k] for p in parts])
                       for k in parts[0]}
                order = np.lexsort((cat["window_start"], cat["key"]))
                rows = {k: v[order] for k, v in cat.items()}
        if rows is None:
            return FiredWindows(data=self._empty())
        return FiredWindows(data=rows)

    def _advance_shard(self, st: _SpanStore) -> Optional[Dict[str, np.ndarray]]:
        """Fire + expiry pass for one shard at the current watermark;
        returns the shard's emitted rows (store order: (key, start))."""
        if not len(st):
            return None
        end1 = st.last + self.gap - 1
        complete = end1 <= self.watermark
        emit = complete & (~st.fired | st.refire)
        rows = None
        if emit.any():
            idx = np.nonzero(emit)[0]
            rows = self._emit(st._take(idx))
            if self.retract:
                # spans whose predecessors were retracted (re)fire as
                # +U; first firings are +I — the row now stands, so the
                # pending-retraction flag clears
                rows[OP_FIELD] = np.where(
                    st.retracted[idx], OP_UPDATE_AFTER,
                    OP_INSERT).astype(OP_DTYPE)
                st.retracted[idx] = False
        st.fired |= complete
        st.refire[:] = False
        dead = end1 + self.lateness <= self.watermark
        if dead.any():
            st._filter(~dead)
        return rows

    def _emit(self, cols: Tuple[np.ndarray, ...]) -> Dict[str, np.ndarray]:
        import jax

        key, start, last, sums, maxs, mins, count = cols[:7]
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            import jax.numpy as jnp

            res = self.agg.finalize(
                jnp.asarray(sums), jnp.asarray(maxs), jnp.asarray(mins),
                jnp.asarray(count.astype(np.int32)))
        out = {
            "key": key.astype(np.int64),
            "window_start": start.astype(np.int64),
            "window_end": (last + self.gap).astype(np.int64),
            "count": count.astype(np.int32),
        }
        # finalize's fields win, including one named "count" — an
        # aggregate built with result_field="count" must not have its
        # output shadowed by the raw record count
        for k, v in res.items():
            out[k] = np.asarray(v)
        return out

    def _empty(self) -> Dict[str, np.ndarray]:
        if not hasattr(self, "_empty_cache"):
            w = self.agg
            self._empty_cache = self._emit((
                np.zeros(0, np.int64), np.zeros(0, np.int64),
                np.zeros(0, np.int64), np.zeros((0, w.sum_width), np.float32),
                np.zeros((0, w.max_width), np.float32),
                np.zeros((0, w.min_width), np.float32),
                np.zeros(0, np.int64)))
            if self.retract:
                self._empty_cache[OP_FIELD] = np.zeros(0, OP_DTYPE)
        return dict(self._empty_cache)

    # -- per-step retraction drain ---------------------------------------
    def take_fired(self):
        """Drain the -U rows merges produced this step (retract mode;
        None otherwise). Called by the driver right after each
        process_batch, so a consumed fired span's retraction reaches
        the sink BEFORE the merged session's eventual (re)fire."""
        from flink_tpu.ops.window import FiredWindows

        if not self._pending_retracts:
            return None
        parts = self._pending_retracts
        self._pending_retracts = []
        if len(parts) == 1:
            rows = parts[0]
        else:
            rows = {k: np.concatenate([p[k] for p in parts])
                    for k in parts[0]}
        # deterministic emission order across host-pool shard counts
        order = np.lexsort((rows["window_start"], rows["key"]))
        return FiredWindows(data={k: v[order] for k, v in rows.items()})

    def final_watermark(self) -> int:
        lasts = [int(st.last.max()) for st in self._shards if len(st)]
        if not lasts:
            return self.watermark if self.watermark != LONG_MIN else 0
        return max(lasts) + self.gap + self.lateness + 1

    # -- snapshot --------------------------------------------------------
    def _merged_columns(self) -> Dict[str, np.ndarray]:
        """The registry's columns as ONE (key, start)-sorted block — the
        checkpoint format is shard-count-independent, so snapshots move
        freely across host.parallelism settings (and stay byte-stable
        for the incremental-checkpoint reuse check)."""
        if len(self._shards) == 1:
            st = self._shards[0]
            return {c: getattr(st, c).copy() for c in st._COLS}
        cols = {c: np.concatenate([getattr(st, c) for st in self._shards])
                for c in _SpanStore._COLS}
        order = np.lexsort((cols["start"], cols["key"]))
        return {c: v[order] for c, v in cols.items()}

    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "watermark": self.watermark,
            "late_records": self.late_records,
            "columns": self._merged_columns(),
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.watermark = snap["watermark"]
        self.late_records = snap["late_records"]
        st = _SpanStore(self.agg.sum_width, self.agg.max_width,
                        self.agg.min_width)
        if "columns" in snap:
            n = len(snap["columns"]["key"])
            for c in st._COLS:
                if c == "retracted" and c not in snap["columns"]:
                    # snapshots predating retract mode: nothing fired as
                    # +I has been merged away yet
                    setattr(st, c, np.zeros(n, bool))
                    continue
                # copy: advance_watermark mutates columns in place
                # (fired |= ..., refire[:] = ...); aliasing the caller's
                # snapshot would corrupt it for reuse (recovery retries,
                # rescale fan-out)
                setattr(st, c, np.array(snap["columns"][c]))
        else:  # legacy per-key dict format (pre-columnar checkpoints)
            rows = [(k, s0, s1, su, mx, mn, ct, fi, rf)
                    for k, spans in snap["spans"].items()
                    for (s0, s1, su, mx, mn, ct, fi, rf) in spans]
            rows.sort(key=lambda r: (r[0], r[1]))
            if rows:
                st.key = np.array([r[0] for r in rows], np.int64)
                st.start = np.array([r[1] for r in rows], np.int64)
                st.last = np.array([r[2] for r in rows], np.int64)
                st.sums = np.stack([r[3] for r in rows]).astype(np.float32)
                st.maxs = np.stack([r[4] for r in rows]).astype(np.float32)
                st.mins = np.stack([r[5] for r in rows]).astype(np.float32)
                st.count = np.array([r[6] for r in rows], np.int64)
                st.fired = np.array([r[7] for r in rows], bool)
                st.refire = np.array([r[8] for r in rows], bool)
                st.retracted = np.zeros(len(rows), bool)
        self._install_store(st)
        self._has_refire = bool(st.refire.any())

    def _install_store(self, st: _SpanStore) -> None:
        """Adopt a merged (key, start)-sorted store, re-sharding it to
        this operator's parallelism (restore is shard-count-agnostic:
        a snapshot taken at W=1 restores into W=4 and vice versa)."""
        n_shards = len(self._shards)
        if n_shards == 1:
            self._shards = [st]
            return
        shards = []
        sh = st.key % n_shards
        for w in range(n_shards):
            part = _SpanStore(self.agg.sum_width, self.agg.max_width,
                              self.agg.min_width)
            m = sh == w
            for c in st._COLS:  # mask keeps (key, start) order per shard
                setattr(part, c, getattr(st, c)[m])
            shards.append(part)
        self._shards = shards
