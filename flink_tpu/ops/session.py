"""Session windows — gap-merged, dynamically-bounded windows.

ref: streaming/api/windowing/assigners/EventTimeSessionWindows.java and
the merge machinery MergingWindowSet.java + WindowOperator's merging
branch (each element opens [ts, ts+gap) and overlapping windows merge,
state merges via namespace re-targeting).

TPU-first redesign (SURVEY §8.4 item 3): dynamic merging cannot be a
static pane layout, so the decomposition is:
- **batch sessionization is vectorized**: sort the microbatch by
  (key, ts); session boundaries are where the key changes or the time
  gap exceeds ``gap``; per-batch-session aggregates come from numpy
  ``reduceat`` segments (C-speed host work — the per-RECORD cost is
  vectorized away, matching how the reference's cost is per element).
- a **host span registry** keeps open sessions per key (tiny: one entry
  per active session, not per record) and merges batch-sessions into
  them — the MergingWindowSet role.
- fired sessions stay in the registry until allowed lateness expires so
  late records re-open/merge and re-fire (late firing semantics).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.ops.aggregates import LaneAggregate
from flink_tpu.time.watermarks import LONG_MIN


@dataclasses.dataclass
class _Span:
    start: int
    last_ts: int          # max event ts in session; end = last_ts + gap
    sums: np.ndarray
    maxs: np.ndarray
    mins: np.ndarray
    count: int
    fired: bool = False   # already emitted once (re-fire on late merge)
    refire: bool = False  # must (re-)emit at the next advance


class SessionOperator:
    """Keyed event-time session aggregation with allowed lateness."""

    def __init__(
        self,
        gap_ms: int,
        agg: LaneAggregate,
        *,
        allowed_lateness_ms: int = 0,
        num_shards: int = 128,
        slots_per_shard: int = 1024,
        max_out_of_orderness_ms: int = 0,
    ) -> None:
        if gap_ms <= 0:
            raise ValueError("session gap must be positive")
        self.gap = int(gap_ms)
        self.agg = agg
        self.lateness = int(allowed_lateness_ms)
        self.watermark = LONG_MIN
        self.late_records = 0
        # key -> list of open/retained spans, disjoint, sorted by start
        self._spans: Dict[int, List[_Span]] = {}
        self._has_refire = False

    # -- ingest ----------------------------------------------------------
    def process_batch(self, keys, ts, data: Dict[str, np.ndarray], valid=None) -> None:
        keys = np.asarray(keys, np.int64)
        ts = np.asarray(ts, np.int64)
        valid = np.ones(len(ts), bool) if valid is None else np.asarray(valid, bool)

        # drop beyond-lateness records (side output accounting): a record
        # is late iff its singleton session is dead AND it cannot merge
        # into any retained span (the reference checks isWindowLate on
        # the POST-merge window — a record touching a live retained
        # session rides that session's lateness)
        if self.watermark != LONG_MIN:
            late = valid & (ts + self.gap - 1 + self.lateness <= self.watermark)
            if late.any():
                for i in np.nonzero(late)[0]:
                    k, t = int(keys[i]), int(ts[i])
                    for sp in self._spans.get(k, ()):
                        if t <= sp.last_ts + self.gap and sp.start <= t + self.gap:
                            late[i] = False
                            break
            self.late_records += int(late.sum())
            valid = valid & ~late
        if not valid.any():
            return
        keys = keys[valid]
        ts = ts[valid]
        data = {k: np.asarray(v)[valid] for k, v in data.items()}

        # vectorized batch sessionization: sort by (key, ts)
        order = np.lexsort((ts, keys))
        sk, st = keys[order], ts[order]
        sdata = {k: v[order] for k, v in data.items()}
        new_seg = np.empty(len(sk), bool)
        new_seg[0] = True
        new_seg[1:] = (sk[1:] != sk[:-1]) | (st[1:] - st[:-1] > self.gap)
        seg_starts = np.nonzero(new_seg)[0]

        # per-segment lane aggregates (host lift on CPU jax → numpy)
        s_l, mx_l, mn_l = self._host_lift(sdata, np.ones(len(sk), bool))
        seg_sum = np.add.reduceat(s_l, seg_starts, axis=0) if s_l.shape[1] else np.zeros((len(seg_starts), 0), np.float32)
        seg_max = np.maximum.reduceat(mx_l, seg_starts, axis=0) if mx_l.shape[1] else np.zeros((len(seg_starts), 0), np.float32)
        seg_min = np.minimum.reduceat(mn_l, seg_starts, axis=0) if mn_l.shape[1] else np.zeros((len(seg_starts), 0), np.float32)
        seg_ends = np.append(seg_starts[1:], len(sk))
        seg_count = seg_ends - seg_starts
        seg_key = sk[seg_starts]
        seg_tmin = st[seg_starts]
        seg_tmax = st[seg_ends - 1]

        # merge batch segments into the registry (MergingWindowSet role)
        for i in range(len(seg_starts)):
            self._merge_span(
                int(seg_key[i]),
                # .copy(): a row view would pin the whole batch's segment
                # arrays in memory for the span's retention lifetime
                _Span(int(seg_tmin[i]), int(seg_tmax[i]),
                      seg_sum[i].copy(), seg_max[i].copy(),
                      seg_min[i].copy(), int(seg_count[i])))

    def _host_lift(self, data, valid) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Run the aggregate's lift on the host CPU backend (session lane
        math is per-batch-segment, tiny — shipping it to the accelerator
        would cost a round trip per batch)."""
        import jax

        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            import jax.numpy as jnp

            s, mx, mn = self.agg.lift_masked(
                {k: jnp.asarray(v) for k, v in data.items()}, jnp.asarray(valid))
            return np.asarray(s), np.asarray(mx), np.asarray(mn)

    def _merge_span(self, key: int, new: _Span) -> None:
        spans = self._spans.setdefault(key, [])
        merged = new
        keep: List[_Span] = []
        refire_needed = False
        for sp in spans:
            # overlap iff [start, last+gap) ranges touch
            if merged.start <= sp.last_ts + self.gap and sp.start <= merged.last_ts + self.gap:
                refire_needed = refire_needed or sp.fired
                merged = _Span(
                    start=min(sp.start, merged.start),
                    last_ts=max(sp.last_ts, merged.last_ts),
                    sums=sp.sums + merged.sums,
                    maxs=np.maximum(sp.maxs, merged.maxs),
                    mins=np.minimum(sp.mins, merged.mins),
                    count=sp.count + merged.count,
                    fired=False,
                    refire=sp.refire or merged.refire,
                )
            else:
                keep.append(sp)
        if refire_needed or (self.watermark != LONG_MIN
                             and merged.last_ts + self.gap - 1 <= self.watermark):
            # late merge into a fired session, or a session already
            # complete at the current watermark → (re-)fire on next advance
            merged.refire = True
            self._has_refire = True
        keep.append(merged)
        keep.sort(key=lambda s: s.start)
        self._spans[key] = keep

    # -- time ------------------------------------------------------------
    def advance_watermark(self, wm: int):
        from flink_tpu.ops.window import FiredWindows

        if wm < self.watermark and not self._has_refire:
            return FiredWindows(data=self._empty())
        self.watermark = max(self.watermark, wm)
        self._has_refire = False
        out_rows: List[Tuple[int, _Span]] = []
        for key, spans in list(self._spans.items()):
            retained: List[_Span] = []
            for sp in spans:
                end = sp.last_ts + self.gap
                complete = end - 1 <= self.watermark
                # merges always produce fired=False spans, so an
                # incomplete refire-flagged span fires naturally at its
                # (new, later) completion — emit only when complete
                if complete and (not sp.fired or sp.refire):
                    out_rows.append((key, sp))
                sp.refire = False
                if end - 1 + self.lateness <= self.watermark:
                    continue  # retention over: drop
                if complete:
                    sp.fired = True
                retained.append(sp)
            if retained:
                self._spans[key] = retained
            else:
                self._spans.pop(key, None)
        if not out_rows:
            return FiredWindows(data=self._empty())
        for _, sp in out_rows:
            sp.fired = True
        return FiredWindows(data=self._emit(out_rows))

    def _emit(self, rows: List[Tuple[int, _Span]]) -> Dict[str, np.ndarray]:
        import jax

        n = len(rows)
        sums = np.stack([sp.sums for _, sp in rows]) if n else np.zeros((0, self.agg.sum_width), np.float32)
        maxs = np.stack([sp.maxs for _, sp in rows]) if n else np.zeros((0, self.agg.max_width), np.float32)
        mins = np.stack([sp.mins for _, sp in rows]) if n else np.zeros((0, self.agg.min_width), np.float32)
        counts = np.array([sp.count for _, sp in rows], np.int32)
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            import jax.numpy as jnp

            res = self.agg.finalize(jnp.asarray(sums), jnp.asarray(maxs),
                                    jnp.asarray(mins), jnp.asarray(counts))
        out = {
            "key": np.array([k for k, _ in rows], np.int64),
            "window_start": np.array([sp.start for _, sp in rows], np.int64),
            "window_end": np.array([sp.last_ts + self.gap for _, sp in rows], np.int64),
            "count": counts,
        }
        for k, v in res.items():
            out[k] = np.asarray(v)
        return out

    def _empty(self) -> Dict[str, np.ndarray]:
        if not hasattr(self, "_empty_cache"):
            self._empty_cache = self._emit([])
        return dict(self._empty_cache)

    def final_watermark(self) -> int:
        mx = LONG_MIN
        for spans in self._spans.values():
            for sp in spans:
                mx = max(mx, sp.last_ts)
        if mx == LONG_MIN:
            return self.watermark if self.watermark != LONG_MIN else 0
        return mx + self.gap + self.lateness + 1

    # -- snapshot --------------------------------------------------------
    def snapshot_state(self) -> Dict[str, Any]:
        return {
            "watermark": self.watermark,
            "late_records": self.late_records,
            "spans": {
                k: [(sp.start, sp.last_ts, sp.sums.copy(), sp.maxs.copy(),
                     sp.mins.copy(), sp.count, sp.fired, sp.refire) for sp in v]
                for k, v in self._spans.items()
            },
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.watermark = snap["watermark"]
        self.late_records = snap["late_records"]
        self._spans = {
            k: [_Span(*t) for t in v] for k, v in snap["spans"].items()
        }
        self._has_refire = any(sp.refire for v in self._spans.values() for sp in v)
