"""Operator factory SPI — the pluggable seam between compiled plan
nodes and runtime operator implementations.

ref: streaming/api/operators/{StreamOperatorFactory,
OneInputStreamOperatorFactory,SimpleOperatorFactory}.java — the
north-star SPI (SURVEY §2): upstream swaps the hot-path implementation
(e.g. a different window operator) by registering a factory, without
touching the user API or the graph compiler. Here the registry maps a
plan-node KIND to a factory; the Driver consults it FIRST, so a
registered factory overrides the built-in construction for that kind —
swap the device kernels behind ``.window().aggregate()`` and every
pipeline picks it up unchanged.

A factory receives the ``ExecNode`` and an ``OperatorBuildContext``
(config-derived knobs + mesh plan) and returns the operator instance.
The built-in window operator registers here too, so the seam is the
REAL construction path, not a bypass for third parties only.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

__all__ = ["OperatorBuildContext", "register_operator_factory",
           "lookup_operator_factory", "unregister_operator_factory"]


@dataclasses.dataclass(frozen=True)
class OperatorBuildContext:
    """Everything a factory may need, pre-resolved from Configuration
    (factories must not re-read raw config — one resolution point)."""

    config: Any
    mesh_plan: Optional[Any]
    num_shards: int
    slots_per_shard: int
    max_inflight_steps: int
    exchange_capacity: Optional[int]
    backend: str
    exchange_impl: str
    max_out_of_orderness_ms: int
    # cross-host jobs: this process's contiguous key-shard span (the
    # key-group range of its "subtask"); None = whole shard space
    shard_range: Optional[Any] = None
    # the driver's shared host worker pool (parallel/hostpool.py) for
    # host-resident operator paths; None = serial
    host_pool: Optional[Any] = None
    # host.fold-chunk-records, the spill store's tree-fold batch floor;
    # None = the declared config default
    fold_chunk_records: Optional[int] = None
    # pipeline.fire-gate: device-side conditional around the fire/top-n/
    # ring-append subgraph of the fused step programs (PROFILE.md §12)
    fire_gate: bool = True
    # pipeline.readiness: 'piggyback' (throttle consumes an announced
    # per-step token) or 'probe' (legacy is_ready spin)
    readiness: str = "piggyback"
    # state.backend='lsm' (disk spill tier, state/lsm.py): memtable
    # budget, run-file root, and the compaction trigger
    memory_budget_bytes: int = 64 * 1024 * 1024
    lsm_dir: str = "/tmp/flink-tpu-state"
    lsm_compact_min_runs: int = 4


OperatorFactory = Callable[[Any, OperatorBuildContext], Any]

_FACTORIES: Dict[str, OperatorFactory] = {}


def register_operator_factory(kind: str, factory: OperatorFactory) -> None:
    _FACTORIES[kind] = factory


def unregister_operator_factory(kind: str) -> None:
    _FACTORIES.pop(kind, None)


def lookup_operator_factory(kind: str) -> Optional[OperatorFactory]:
    return _FACTORIES.get(kind)


# -- built-in factories (the default hot path registers through its own
# seam; ref: SimpleOperatorFactory wrapping the built-in operators) ----

def _window_factory(node, ctx: OperatorBuildContext):
    from flink_tpu.ops.window import WindowOperator

    t = node.window_transform
    spill_store = None
    if ctx.backend == "lsm":
        import os
        import uuid

        from flink_tpu.state.lsm import LsmSpillStore

        # unique per operator INSTANCE: run files are owned by one
        # store for its lifetime (checkpoints hardlink them out; a
        # restore links them back into the successor's fresh dir)
        store_dir = os.path.join(
            ctx.lsm_dir,
            f"op{node.id}-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        spill_store = LsmSpillStore(
            t.aggregate, store_dir=store_dir,
            memory_budget_bytes=ctx.memory_budget_bytes,
            num_shards=ctx.num_shards,
            compact_min_runs=ctx.lsm_compact_min_runs,
            pool=ctx.host_pool,
            fold_chunk_records=ctx.fold_chunk_records)
    op = WindowOperator(
        t.assigner, t.aggregate,
        num_shards=ctx.num_shards,
        slots_per_shard=ctx.slots_per_shard,
        allowed_lateness_ms=t.allowed_lateness_ms,
        max_out_of_orderness_ms=max(ctx.max_out_of_orderness_ms, 0),
        mesh_plan=ctx.mesh_plan,
        shard_range=ctx.shard_range,
        top_n=t.top_n,
        exchange_capacity=ctx.exchange_capacity,
        spill=(ctx.backend == "spill"),
        spill_store=spill_store,
        exchange_impl=ctx.exchange_impl,
        host_pool=ctx.host_pool,
        fold_chunk_records=ctx.fold_chunk_records,
        fire_gate=ctx.fire_gate,
        readiness=ctx.readiness,
    )
    op.max_inflight_steps = ctx.max_inflight_steps
    # backpressure blocks happen OUTSIDE the push lock (the ingest loop
    # calls throttle() after releasing it), so drain deliveries never
    # queue behind a transfer wait
    op.external_throttle = True
    return op


register_operator_factory("window", _window_factory)
