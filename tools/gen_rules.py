#!/usr/bin/env python3
"""Regenerate RULES.md from the analyzer rule registrations::

    python tools/gen_rules.py

The catalog is rendered by flink_tpu/analysis/docs.py from
core.rule_catalog_full() + pylints.LINT_CATALOG; the tier-1 staleness
gate (tests/test_analysis.py) asserts the committed RULES.md matches,
so run this after adding or editing a rule.
"""
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from flink_tpu.analysis.docs import render_rules_md  # noqa: E402

if __name__ == "__main__":
    out = os.path.join(ROOT, "RULES.md")
    with open(out, "w", encoding="utf-8") as f:
        f.write(render_rules_md())
    print(f"wrote {out}")
