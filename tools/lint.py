#!/usr/bin/env python3
"""Repo AST lints, runnable straight from a checkout::

    python tools/lint.py [PATH ...] [--json]

Thin wrapper over ``python -m flink_tpu lint`` (the rules live in
flink_tpu/analysis/pylints.py) so CI and pre-commit hooks can invoke
the linter without installing the package: it puts the repo root on
sys.path itself. Exit status 1 when any finding fires — the shipped
tree is kept at zero findings by the tier-1 dogfood gate
(tests/test_analysis.py).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flink_tpu.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["lint", *sys.argv[1:]]))
