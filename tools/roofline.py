"""Device-kernel roofline for the Q5 hot path (PROFILE.md device section).

Measures ON-CHIP time for each kernel the Q5 pipeline dispatches —
apply (3B split upload), apply (packed i32), fire+topn+ring append,
clear — at the benchmark shape (2^20-record batches, 128x256 slots,
ring 16, count aggregate), plus candidate kernels for the next
optimization step (host pre-aggregated sparse apply at several pair
counts). Reports per-kernel ms and achieved HBM GB/s against the
tensor traffic each kernel necessarily moves.

Method: upload inputs once, chain N donated kernel steps, block once;
per-step time = (t_chain - t_noop) / N. The chain amortizes the
tunnel's ~100ms block_until_ready round trip so the number is device
time, not link time.

Run: JAX_PLATFORMS=<backend> python tools/roofline.py
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ops import aggregates
from flink_tpu.ops.window import (
    _JIT_APPLY, _JIT_APPLY_SPLIT, _JIT_CLEAR, _JIT_RING_TOPN,
    split_encode, _next_pow2,
)
from flink_tpu.state.keyed import PaneStateLayout, init_state

B = 1 << 20          # benchmark microbatch
SLOTS = 128 * 256    # 128 shards x 256 slots
RING = 16            # Q5 plan: 10s/1s sliding + 1s ooo -> ring 16
NKEYS = 10_000       # active auctions
PANES_PER_BATCH = 11 # 2^20 records at 100 ev/ms spans ~10.5s of event time
W = 10               # window-ends per advance (one advance per batch)
PPW = 10


def _mk_state(layout):
    return init_state(layout)


def time_chain(fn, state, *args, n=24):
    """Per-call seconds for `state = fn(state, *args)` chained n times."""
    # warm compile + one settle
    state = fn(state, *args)
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for _ in range(n):
        state = fn(state, *args)
    jax.block_until_ready(state)
    t1 = time.perf_counter()
    return (t1 - t0) / n, state


def time_chain_ring(fn, ring_buf, state, *args, n=24):
    """Same, but the mutated operand is the emit ring (arg 0 stays)."""
    ring_buf = fn(state, ring_buf, *args)
    jax.block_until_ready(ring_buf)
    t0 = time.perf_counter()
    for _ in range(n):
        ring_buf = fn(state, ring_buf, *args)
    jax.block_until_ready(ring_buf)
    t1 = time.perf_counter()
    return (t1 - t0) / n, ring_buf


def h2d_seconds(arr_np, n=8):
    """Steady-state host->device seconds per transfer (forced consume)."""
    probe = jax.jit(lambda x: x.reshape(-1)[:1].astype(jnp.int32).sum())
    x = jnp.asarray(arr_np)
    jax.block_until_ready(probe(x))
    t0 = time.perf_counter()
    for _ in range(n):
        x = jnp.asarray(arr_np)
        jax.block_until_ready(probe(x))
    t1 = time.perf_counter()
    return (t1 - t0) / n


def main():
    agg = aggregates.count()
    layout = PaneStateLayout(slots=SLOTS, ring=RING, sum_width=agg.sum_width,
                             max_width=agg.max_width, min_width=agg.min_width)
    rows = layout.rows
    rng = np.random.default_rng(7)
    print(f"# backend={jax.default_backend()} rows={rows} ring={RING} B={B}")
    out = {}

    # --- input shapes (Q5-realistic: 10k hot-skewed keys, ~11 panes) ---
    slots = rng.integers(0, NKEYS, B).astype(np.int64)
    cols = (rng.integers(0, PANES_PER_BATCH, B) % RING).astype(np.uint8)
    valid = np.ones(B, bool)

    # --- apply: 3-byte split upload (current bench path) ---
    sc = split_encode(slots, cols, valid)
    sc_d = jnp.asarray(sc)
    jax.block_until_ready(sc_d)
    state = _mk_state(layout)
    import functools
    apply_split = functools.partial(_JIT_APPLY_SPLIT, agg=agg, dump_row=SLOTS)
    dt, state = time_chain(lambda s, b: apply_split(s, b, {}), state, sc_d)
    # traffic floor: read 3B*B input + counts r/w is sparse (<= B cells)
    out["apply_split_ms"] = dt * 1e3
    out["apply_split_Mrec_s"] = B / dt / 1e6

    # --- apply: packed i32 (4B) path ---
    packed = (slots * RING + cols).astype(np.int32)
    pk_d = jnp.asarray(packed)
    jax.block_until_ready(pk_d)
    apply_p = functools.partial(_JIT_APPLY, agg=agg, ring=RING, dump_row=SLOTS)
    state2 = _mk_state(layout)
    dt, state2 = time_chain(lambda s, b: apply_p(s, b, {}), state2, pk_d)
    out["apply_packed_ms"] = dt * 1e3
    out["apply_packed_Mrec_s"] = B / dt / 1e6

    # --- candidate: pre-aggregated sparse apply at several pair counts ---
    # host combiner ships (pair_id, count) for the <=(keys x panes) pairs
    # a batch actually touches; the scatter shrinks by B/P.
    def apply_agg(counts, pairs, cnts):
        pid = pairs
        ok = pid >= 0
        r = jnp.where(ok, pid // RING, SLOTS).astype(jnp.int32)
        c = jnp.where(ok, pid % RING, 0).astype(jnp.int32)
        return counts.at[r, c].add(jnp.where(ok, cnts, 0))

    japply_agg = jax.jit(apply_agg, donate_argnums=(0,))
    for cap_pow in (17, 18):
        P = 1 << cap_pow
        pairs = np.full(P, -1, np.int32)
        npair = min(NKEYS * PANES_PER_BATCH, P)
        pairs[:npair] = rng.choice(SLOTS * RING, npair, replace=False)
        cnts = np.full(P, B // max(npair, 1), np.int32)
        pr_d, ct_d = jnp.asarray(pairs), jnp.asarray(cnts)
        jax.block_until_ready((pr_d, ct_d))
        counts = jnp.zeros((rows, RING), jnp.int32)
        dt, counts = time_chain(lambda s, p, c: japply_agg(s, p, c),
                                counts, pr_d, ct_d)
        out[f"apply_preagg_2e{cap_pow}_ms"] = dt * 1e3

    # --- fire + top-n + emit-ring append (the per-advance kernel) ---
    by, topn = "count", 1
    sel_cap = _next_pow2(8 * 64)
    ring_topn = functools.partial(
        _JIT_RING_TOPN, agg=agg, panes_per_window=PPW, ring=RING,
        by=by, topn=topn, sel_cap=sel_cap)
    n_res = 1  # count()
    emit_ring = jnp.zeros((8192 + 2, 3 + n_res), jnp.int32)
    ends = np.arange(100, 100 + W, dtype=np.int64)
    params = np.concatenate([[90, 111, 90], ends]).astype(np.int64)
    params_d = jnp.asarray(params)
    used = jnp.ones((rows,), bool)
    jax.block_until_ready((params_d, used))
    dt, emit_ring = time_chain_ring(
        lambda s, r, p, u: ring_topn(s, r, p, u), emit_ring, state2,
        params_d, used)
    out["fire_topn_W10_ms"] = dt * 1e3
    # necessary traffic: counts gather rows x W x ppw x 4B (widths are 0)
    fire_bytes = rows * W * PPW * 4
    out["fire_topn_GBs"] = fire_bytes / dt / 1e9

    # --- clear ---
    cmask = np.zeros(RING, bool)
    cmask[:2] = True
    cm_d = jnp.asarray(cmask)
    jax.block_until_ready(cm_d)
    state3 = _mk_state(layout)
    dt, state3 = time_chain(lambda s, m: _JIT_CLEAR(s, m), state3, cm_d)
    out["clear_ms"] = dt * 1e3
    out["clear_GBs"] = (rows * RING * 4 * 2) / dt / 1e9

    # --- transport reference points (steady-state, forced consume) ---
    out["h2d_3MB_ms"] = h2d_seconds(sc) * 1e3              # 3B/rec batch
    out["h2d_1MB_ms"] = h2d_seconds(
        np.zeros((1 << 17, 8), np.uint8)) * 1e3            # pair buffer
    out["h2d_4MB_ms"] = h2d_seconds(packed) * 1e3          # 4B/rec batch

    for k, v in out.items():
        print(f"{k}: {v:.3f}")
    print(json.dumps({k: round(v, 3) for k, v in out.items()}))


if __name__ == "__main__":
    main()
