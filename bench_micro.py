"""Micro-benchmarks (`python bench_micro.py`) — the flink-benchmarks
analogue BASELINE.md's bottom section names:

1. keyed state update ops/sec (HBM pane scatter-add) per chip
2. keyBy all_to_all sustained GB/s over the mesh axis vs record size
3. host ingest codec MB/s (C parser, single core)
4. window-fire flush latency (watermark advance → fired rows on host)
5. checkpoint snapshot bytes/sec + resume time vs state size

One JSON line per metric. Runs on whatever backend is live (the real
chip under the driver; CPU elsewhere — collective numbers on the
virtual mesh measure the code path, not ICI, and say so).
"""
from __future__ import annotations

import json
import time

import numpy as np


def _line(metric: str, value: float, unit: str, **extra) -> None:
    print(json.dumps({"metric": metric, "value": round(value, 3),
                      "unit": unit, **extra}), flush=True)


def _emit(rows: list, metric: str, value: float, unit: str,
          **extra) -> None:
    """Print one metric line AND collect it for the artifact — the two
    must never diverge (the artifact's whole point is that claims are
    recorded numbers)."""
    rows.append({"metric": metric, "value": round(value, 3),
                 "unit": unit, **extra})
    _line(metric, value, unit, **extra)


def _write_artifact(path: str, bench: str, rows: list, **extra) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"bench": bench, **extra, "lines": rows}, f, indent=1)
    print(f"# {bench} artifact -> {path}", flush=True)


def bench_state_update(batch: int = 1 << 20, iters: int = 12) -> None:
    """#1: pane scatter-add ops/sec — apply_kernel_split on a Q5-shaped
    layout, pipelined like the driver (inflight steps)."""
    import jax

    from flink_tpu.api.windowing import SlidingEventTimeWindows
    from flink_tpu.ops import aggregates
    from flink_tpu.ops.window import WindowOperator, split_encode

    op = WindowOperator(SlidingEventTimeWindows.of(10_000, 1_000),
                        aggregates.count(),
                        num_shards=128, slots_per_shard=256)
    rng = np.random.default_rng(0)
    slots = rng.integers(0, 32_000, batch)
    cols = rng.integers(0, op.plan.ring, batch).astype(np.uint8)
    valid = np.ones(batch, bool)
    sc_host = split_encode(slots, cols, valid)
    import jax.numpy as jnp

    # warmup
    op.state = op._apply_split(op.state, jnp.asarray(sc_host), {})
    jax.block_until_ready(op.state.counts)
    t0 = time.perf_counter()
    for _ in range(iters):
        op.state = op._apply_split(op.state, jnp.asarray(sc_host), {})
    total = int(op.state.counts[0, 0])  # force full sync
    el = time.perf_counter() - t0
    _line("state_update_ops_per_sec", batch * iters / el, "records/sec",
          note="incl. host->device upload (the real ingest path)")
    del total


def bench_all_to_all(iters: int = 8) -> None:
    """#2: keyBy exchange sustained GB/s over the mesh axis, per record
    size. On the virtual CPU mesh this measures the code path, not ICI."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from flink_tpu.exchange.spi import all_to_all_shuffle
    from flink_tpu.parallel.mesh import AXIS, make_mesh_plan
    from flink_tpu.utils.jaxcompat import shard_map

    n_dev = len(jax.devices())
    if n_dev < 2:
        _line("keyby_exchange_gbps", 0.0, "GB/s",
              note="single device: exchange is a no-op, skipped")
        return
    mp = make_mesh_plan(n_dev * 2, 4, devices=jax.devices())
    # ACTUAL payload bytes per record: one int64 key + width float32
    # fields (the reported GB/s must count what actually moved)
    for width in (1, 15):
        rec_bytes = 8 + 4 * width
        b = n_dev * (1 << 14)
        cap = (1 << 14)
        rng = np.random.default_rng(1)
        dest = jnp.asarray(rng.integers(0, n_dev, b).astype(np.int32))
        valid = jnp.ones(b, bool)
        payload = {"k": jnp.asarray(rng.integers(0, 1000, b).astype(np.int64))}
        for i in range(width):
            payload[f"f{i}"] = jnp.asarray(
                rng.random(b).astype(np.float32))

        def shard(dest, valid, payload):
            from jax import lax

            recv, rv, ov = all_to_all_shuffle(
                dest, valid, payload, n_devices=n_dev, capacity=cap)
            local = sum(jnp.sum(v.astype(jnp.float32))
                        for v in recv.values())
            return lax.psum(local, AXIS)

        spec = {k: P(AXIS) for k in payload}
        fn = jax.jit(shard_map(
            shard, mesh=mp.mesh, in_specs=(P(AXIS), P(AXIS), spec),
            out_specs=P()))
        float(fn(dest, valid, payload))  # warm
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(dest, valid, payload)
        float(r)
        el = time.perf_counter() - t0
        gb = b * rec_bytes * iters / 1e9
        _line("keyby_exchange_gbps", gb / el, "GB/s",
              record_bytes=rec_bytes, devices=n_dev,
              note="virtual CPU mesh measures the code path, not ICI"
              if jax.devices()[0].platform == "cpu" else "on-chip")


def bench_codec(mb: int = 64) -> None:
    """#3: host ingest codec MB/s — C CSV parser, single core."""
    from flink_tpu import native_codec

    rng = np.random.default_rng(2)
    rows = 1 << 18
    table = rng.integers(0, 10**9, (rows, 3)).astype(np.int64)
    blob = native_codec.encode_i64_rows(table)
    reps = max(1, int(mb * 1e6 / len(blob)))
    t0 = time.perf_counter()
    for _ in range(reps):
        out = native_codec.parse_i64_table(blob, 3)
    el = time.perf_counter() - t0
    assert out.shape[0] == rows
    _line("ingest_codec_mb_per_sec", len(blob) * reps / 1e6 / el, "MB/s",
          native=native_codec.native_available())


def bench_columnar(sizes=(1 << 16, 1 << 20, 1 << 24),
                   artifact: str | None = None,
                   target_x: float = 5.0) -> list:
    """Columnar codec axis (ISSUE 13): encode + decode bytes/s of the
    at-rest format (``formats_columnar``) across payload size, decode
    mode (copy vs zero-copy) and CRC implementation (zlib vs native
    PCLMUL) — the copy x zlib cell is the pre-PR path, zero-copy x
    native the shipped one. One ``columnar_decode_speedup`` line per
    size records the ratio with ``target_met`` against the
    >=``target_x`` bar at the 1MB point. Single-threaded by
    construction (one buffer, one reader) — the GIL-free property of
    the native CRC additionally lets CONCURRENT readers overlap, which
    a single-core container cannot show; the artifact says so rather
    than implying it."""
    import zlib

    from flink_tpu import formats_columnar as fc
    from flink_tpu import native_codec

    rows: list = []

    def emit(metric, value, unit, **extra):
        _emit(rows, metric, value, unit, **extra)

    rng = np.random.default_rng(5)
    native = native_codec.native_available()
    decode_by: dict = {}
    for size in sizes:
        # i64-heavy batch (the log tier's shape: keys/ts/values), one
        # block per file image — `size` is the approximate payload
        nrows = max(size // (4 * 8), 16)
        batch = {
            "k": rng.integers(0, 1 << 40, nrows).astype(np.int64),
            "ts": np.arange(nrows, dtype=np.int64),
            "a": rng.integers(0, 10_000, nrows).astype(np.int64),
            "v": rng.random(nrows).astype(np.float64),
        }
        fmt = fc.ColumnarFormat(fc.infer_schema(batch))
        image = fmt.serialize(batch)
        nbytes = len(image)
        reps = max(3, int((1 << 28) / nbytes))
        for crc_name in ("zlib", "native"):
            if crc_name == "native" and not native:
                emit("columnar_codec_skipped", 0.0, "n/a",
                     constraint="native codec library unavailable "
                                "(no compiler?) — zlib cells only")
                continue
            real = fc._crc32
            fc._crc32 = zlib.crc32 if crc_name == "zlib" else real
            try:
                t0 = time.perf_counter()
                for _ in range(reps):
                    buf = fmt.serialize(batch)
                el = time.perf_counter() - t0
                emit("columnar_encode_bytes_per_sec",
                     nbytes * reps / el, "bytes/s",
                     size=nbytes, crc=crc_name,
                     note="scatter write path (no payload concat)")
                for zero_copy in (False, True):
                    t0 = time.perf_counter()
                    for _ in range(reps):
                        for blk in fc.iter_blocks(
                                memoryview(image), zero_copy=zero_copy):
                            pass
                    el = time.perf_counter() - t0
                    emit("columnar_decode_bytes_per_sec",
                         nbytes * reps / el, "bytes/s",
                         size=nbytes, crc=crc_name,
                         mode="zero_copy" if zero_copy else "copy")
                    decode_by[(size, crc_name,
                               "zero_copy" if zero_copy else "copy")] = (
                        nbytes * reps / el)
            finally:
                fc._crc32 = real
        del buf
        base = decode_by.get((size, "zlib", "copy"))
        new = decode_by.get((size, "native", "zero_copy"))
        if base and new:
            extra = {}
            if size == 1 << 20:
                extra["target_met"] = bool(new / base >= target_x)
                extra["target"] = f">= {target_x}x at 1MB"
            emit("columnar_decode_speedup", new / base, "x",
                 size=nbytes, compare="zero_copy+native vs copy+zlib",
                 note="single-threaded decode of one image; the "
                      "native CRC is additionally GIL-free, so "
                      "concurrent readers overlap where cores exist "
                      "(this container schedules 1 core)", **extra)
    if artifact:
        _write_artifact(
            artifact, "columnar_codec", rows,
            native_codec=native,
            host_cores=len(__import__("os").sched_getaffinity(0)))
    return rows


def bench_fire_flush(iters: int = 10) -> None:
    """#4: watermark advance → fired rows decoded on host."""
    from flink_tpu.api.windowing import SlidingEventTimeWindows
    from flink_tpu.ops import aggregates
    from flink_tpu.ops.window import WindowOperator

    rng = np.random.default_rng(3)
    op = WindowOperator(SlidingEventTimeWindows.of(10_000, 1_000),
                        aggregates.count(),
                        num_shards=64, slots_per_shard=128)
    op.allow_drops = True  # micro bench measures latency, not capacity
    lat = []
    for i in range(iters + 2):
        n = 1 << 16
        keys = rng.integers(0, 5_000, n)
        ts = rng.integers(i * 2_000, i * 2_000 + 4_000, n)
        op.process_batch(keys, ts, {})
        op.quiesce()
        t0 = time.perf_counter()
        fired = op.advance_watermark(i * 2_000)
        rows = len(fired["key"])  # forces the fetch + decode
        if i >= 2:
            lat.append(time.perf_counter() - t0)
    _line("window_fire_flush_ms", 1e3 * float(np.median(lat)), "ms",
          p99=round(1e3 * float(np.quantile(lat, 0.99)), 3))


def bench_control(iters: int = 150,
                  artifact: str | None = None) -> list:
    """#6: control-plane readiness probe (PROFILE.md §12 / §8.3 lever
    a) — per-wait cost of retiring one in-flight device step under the
    two ``pipeline.readiness`` mechanisms:

    - ``probe``: ``hostsync.ready_wait`` — an ``is_ready()`` spin with
      a 2ms sleep quantum (the pre-§12 throttle). On this CPU backend
      each probe is a local flag read, so the measured overhead is the
      poll-quantum overshoot; on the measured remote-attached relay
      EVERY probe is a control round trip (~tens of ms, §8.3) — the
      honest constraint line says which regime this artifact measured.
    - ``piggyback``: consume a tiny ``copy_to_host_async``-announced
      output of the same dispatch (``np.asarray`` blocks on the
      in-flight transfer only — no poll loop, no extra round trip).

    Reported as per-wait MICROSECONDS OVER the pure compute wall
    (block_until_ready baseline), plus the ratio."""
    import os

    import jax
    import jax.numpy as jnp

    from flink_tpu.hostsync import ready_wait

    rows: list = []

    @jax.jit
    def step(x):
        # enough work that the dispatch is genuinely in flight when the
        # wait starts (a few hundred µs on one CPU core)
        for _ in range(4):
            x = jnp.tanh(x @ x)
        return x, x[0, :8]

    x = jnp.asarray(np.random.default_rng(7).random((384, 384),
                                                    np.float32))
    out, tok = step(x)  # compile
    jax.block_until_ready(out)

    def run(mode: str) -> float:
        t0 = time.perf_counter()
        for _ in range(iters):
            out, tok = step(x)
            if mode == "block":
                jax.block_until_ready(out)
            elif mode == "probe":
                ready_wait(out)
            else:  # piggyback
                tok.copy_to_host_async()
                np.asarray(tok)
        return (time.perf_counter() - t0) / iters

    base = run("block")
    run("probe")  # warm both wait paths before the measured passes
    probe = run("probe")
    piggy = run("piggyback")
    cores = len(os.sched_getaffinity(0))
    constraint = (
        f"{jax.default_backend()} backend, {cores} schedulable core(s): "
        "is_ready here is a local flag read, so probe overhead is the "
        "2ms poll-quantum overshoot only — on the remote-attached relay "
        "every is_ready probe is a control round trip (~tens of ms, "
        "PROFILE.md §8.3) and the piggyback gap widens accordingly")
    over_probe = max(0.0, probe - base) * 1e6
    over_piggy = max(0.0, piggy - base) * 1e6
    _emit(rows, "control_wait_us_probe", over_probe, "us/wait",
          mode="is_ready spin (pipeline.readiness=probe)",
          wall_us=round(probe * 1e6, 1), constraint=constraint)
    _emit(rows, "control_wait_us_piggyback", over_piggy, "us/wait",
          mode="announced-transfer consume (pipeline.readiness="
               "piggyback)", wall_us=round(piggy * 1e6, 1),
          constraint=constraint)
    # the robust headline is the absolute per-wait saving — the ratio's
    # denominator can measure below timer noise (piggyback overhead ~0),
    # so it is floored and flagged rather than reported as a silly
    # divide-by-epsilon number
    _emit(rows, "control_wait_saved_us", over_probe - over_piggy,
          "us/wait",
          note="per-wait overhead removed by piggybacked readiness "
               "(probe minus piggyback, each over the "
               "block_until_ready baseline)", constraint=constraint)
    floor_us = 5.0
    _emit(rows, "control_readiness_speedup",
          over_probe / max(over_piggy, floor_us), "x",
          note="per-wait overhead ratio; >1 = piggybacked readiness "
               "retires a step cheaper than the is_ready spin",
          denominator_floored=over_piggy < floor_us,
          floor_us=floor_us, constraint=constraint,
          host_cores=cores)
    if artifact:
        _write_artifact(artifact, "control_plane", rows,
                        backend=jax.default_backend(), host_cores=cores,
                        iters=iters)
    return rows


def bench_checkpoint(tmp: str | None = None) -> None:
    """#5: snapshot bytes/sec (HBM→host→store) and resume time."""
    import shutil
    import tempfile

    from flink_tpu.api.windowing import SlidingEventTimeWindows
    from flink_tpu.checkpoint.coordinator import CheckpointCoordinator
    from flink_tpu.checkpoint.storage import FsCheckpointStorage
    from flink_tpu.ops import aggregates
    from flink_tpu.ops.window import WindowOperator

    d = tmp or tempfile.mkdtemp(prefix="bench_ckpt_")
    rng = np.random.default_rng(4)
    op = WindowOperator(SlidingEventTimeWindows.of(10_000, 1_000),
                        aggregates.multi(aggregates.count(),
                                         aggregates.sum_of("v")),
                        num_shards=128, slots_per_shard=256)
    op.allow_drops = True  # 30k keys over 32k slots: shard-skew drops ok
    n = 1 << 19
    op.process_batch(rng.integers(0, 30_000, n),
                     rng.integers(0, 20_000, n),
                     {"v": rng.random(n).astype(np.float32)})
    op.quiesce()
    coord = CheckpointCoordinator(FsCheckpointStorage(d, "bench"))
    t0 = time.perf_counter()
    h = coord.trigger(lambda: {"operators": {"0": op.snapshot_state()}},
                      commit_fns=[], prepare_fns=[])
    el = time.perf_counter() - t0
    size = getattr(h, "size_bytes", 0) or 0
    _line("checkpoint_bytes_per_sec", size / max(el, 1e-9) / 1e6, "MB/s",
          snapshot_bytes=size, wall_ms=round(1e3 * el, 1))
    t0 = time.perf_counter()
    payload = coord.restore_latest()
    op2 = WindowOperator(SlidingEventTimeWindows.of(10_000, 1_000),
                         aggregates.multi(aggregates.count(),
                                          aggregates.sum_of("v")),
                         num_shards=128, slots_per_shard=256)
    ops = payload["operators"]
    op2.restore_state(ops.get(0, ops.get("0")))
    el = time.perf_counter() - t0
    _line("checkpoint_resume_ms", 1e3 * el, "ms", state_bytes=size)
    if tmp is None:
        shutil.rmtree(d, ignore_errors=True)


def bench_dcn(payloads=(0, 64 * 1024, 1 << 20), procs=(2, 4),
              iters: int = 30, codecs=("legacy", "binary"),
              artifact: str | None = None,
              target_x: float = 5.0) -> list:
    """Cross-host exchange cost (exchange/dcn.py): per-step rendezvous
    wall time vs payload size, process count, AND wire codec —
    ``legacy`` is the pre-rebuild serial blobformat plane kept
    byte-for-byte as the baseline, ``binary`` is the production plane
    (fixed binary frames + parallel per-peer I/O, ISSUE 12). One
    ``dcn_codec_speedup`` line per (procs, payload) records the
    binary/legacy bytes-per-second ratio with ``target_met`` against
    the >=``target_x`` bar at 1MB, and ``artifact`` (a path) persists
    every line as JSON so the claim is a recorded number, not a log
    grep. In-process threads over loopback — measures the framework's
    framing + barrier costs (the wire is the hardware's job)."""
    import threading

    import numpy as np

    from flink_tpu.exchange.dcn import DcnExchange

    rows: list = []

    def emit(metric, value, unit, **extra):
        _emit(rows, metric, value, unit, **extra)

    step_by: dict = {}
    for codec in codecs:
        for n in procs:
            for nbytes in payloads:
                exs = [DcnExchange(i, n, codec=codec) for i in range(n)]
                peers = [f"127.0.0.1:{e.port}" for e in exs]
                per_peer = max(nbytes // max(n - 1, 1), 0)
                share = np.zeros(per_peer // 8 or 1, np.int64)
                times = [0.0] * n

                def run(i):
                    exs[i].connect(peers)
                    shares = {j: share for j in range(n) if j != i}
                    # warm
                    exs[i].exchange(shares, {"wm": 0})
                    t0 = time.perf_counter()
                    for k in range(iters):
                        exs[i].exchange(shares, {"wm": k})
                    times[i] = (time.perf_counter() - t0) / iters

                ths = [threading.Thread(target=run, args=(i,))
                       for i in range(n)]
                for t in ths:
                    t.start()
                for t in ths:
                    t.join(timeout=120)
                for e in exs:
                    e.close()
                step_ms = max(times) * 1000
                if step_ms <= 0:
                    raise RuntimeError(
                        f"dcn bench barrier failed (n={n}, {nbytes}B, "
                        f"{codec}): a peer thread never completed")
                step_by[(codec, n, nbytes)] = step_ms
                emit("dcn_exchange_step_ms", step_ms, "ms/step",
                     n_processes=n, payload_bytes=nbytes, codec=codec)
                if nbytes:
                    emit("dcn_exchange_bytes_per_sec",
                         nbytes / (step_ms / 1000), "bytes/sec",
                         n_processes=n, payload_bytes=nbytes,
                         codec=codec)
                    emit("dcn_exchange_records_per_sec",
                         (nbytes / 12) / (step_ms / 1000), "records/sec",
                         n_processes=n, payload_bytes=nbytes,
                         record_bytes=12, codec=codec)
    if "legacy" in codecs and "binary" in codecs:
        import os

        for n in procs:
            for nbytes in payloads:
                if not nbytes:
                    continue
                sp = (step_by[("legacy", n, nbytes)]
                      / step_by[("binary", n, nbytes)])
                extra = {}
                # honest-constraint convention (bench.py
                # --host-parallelism): this bench runs every endpoint
                # as a THREAD of one interpreter, so the parallel I/O
                # plane and the per-peer checksum threads only overlap
                # when each endpoint has roughly a core to itself; on
                # fewer cores the measurement is a single-core codec
                # comparison, not a data-plane scaling number
                cores = len(os.sched_getaffinity(0))
                if cores < 2 * n:
                    extra["constraint"] = (
                        f"insufficient-cores ({cores} available, "
                        f"{2 * n} wanted: in-process endpoints share "
                        "cores AND one GIL — parallel peer I/O cannot "
                        "overlap here; run on the chip host)")
                emit("dcn_codec_speedup", sp, "x", n_processes=n,
                     payload_bytes=nbytes,
                     target=target_x if nbytes == 1 << 20 else None,
                     target_met=(sp >= target_x
                                 if nbytes == 1 << 20 else None),
                     **extra)
    if artifact:
        _write_artifact(artifact, "dcn_exchange", rows, iters=iters)
    return rows


_Q5_WORKER = r"""
import json, os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
from flink_tpu.api.environment import StreamExecutionEnvironment
from flink_tpu.api.sources import GeneratorSource
from flink_tpu.api.windowing import TumblingEventTimeWindows
from flink_tpu.config import Configuration
from flink_tpu.time.watermarks import WatermarkStrategy

pid = int(sys.argv[1]); n = int(sys.argv[2]); peers = sys.argv[3]
my_port = int(sys.argv[4]); n_batches = int(sys.argv[5])
batch = int(sys.argv[6])

def gen(split, i):
    if i >= n_batches:
        return None
    rng = np.random.default_rng(31 + 1000 * int(split) + i)
    return ({{"k": rng.integers(0, 256, batch).astype(np.int64)}},
            i * 1000 + rng.integers(0, 1000, batch).astype(np.int64))

conf = {{"state.num-key-shards": 8, "state.slots-per-shard": 512,
         "pipeline.microbatch-size": batch}}
if n > 1:
    conf.update({{"cluster.num-processes": n, "cluster.process-id": pid,
                  "cluster.dcn-peers": peers,
                  "cluster.dcn-port": my_port}})
env = StreamExecutionEnvironment(Configuration(conf))
(env.from_source(GeneratorSource(gen, n_splits=2),
                 WatermarkStrategy.for_bounded_out_of_orderness(1000))
 .key_by("k").window(TumblingEventTimeWindows.of(1000)).count()
 .collect())
t0 = time.perf_counter()
env.execute("q5-scale")
print(json.dumps({{"wall_s": time.perf_counter() - t0}}), flush=True)
"""


def bench_dcn_q5(procs: int = 2, n_batches: int = 24,
                 batch: int = 1 << 12, force: bool = False,
                 artifact: str | None = None) -> list:
    """The 2-process Q5 throughput-scaling run of ROADMAP item 2: the
    same keyed-window job as one process vs ``procs`` processes through
    the DCN plane (binary frames + parallel I/O + overlap), events/s
    clocked INSIDE each worker (interpreter + jit warm-up excluded).
    ``dcn_q5_scaling`` records throughput(N)/throughput(1) with
    ``target_met`` = scales past 1x; on a host without at least a core
    per process it emits the honest SKIPPED line instead (parity —
    byte-identical committed output — is proven in tier-1 regardless,
    tests/test_dcn.py). ``force`` runs the measurement anyway
    (validation on small hosts)."""
    import json as _json
    import os
    import socket
    import subprocess
    import sys
    import tempfile

    rows: list = []

    def emit(metric, value, unit, **extra):
        _emit(rows, metric, value, unit, **extra)

    cores = len(os.sched_getaffinity(0))
    if cores < 2 * procs and not force:
        emit("dcn_q5_scaling", 0.0, "ratio", skipped=(
            f"insufficient-cores ({cores} available): {procs}-process "
            "Q5 throughput scaling needs >= 1 core per process — run "
            "on the chip host; parity is proven in tier-1 "
            "(tests/test_dcn.py)"))
    else:
        repo = os.path.dirname(os.path.abspath(__file__))
        script = os.path.join(tempfile.mkdtemp(prefix="dcn-q5-"),
                              "worker.py")
        with open(script, "w", encoding="utf-8") as f:
            f.write(_Q5_WORKER.format(repo=repo))
        env = dict(os.environ, JAX_PLATFORMS="cpu")

        def fleet(n):
            socks = [socket.socket() for _ in range(n)]
            for s in socks:
                s.bind(("127.0.0.1", 0))
            ports = [s.getsockname()[1] for s in socks]
            for s in socks:
                s.close()
            peers = ",".join(f"127.0.0.1:{p}" for p in ports)
            ps = [subprocess.Popen(
                [sys.executable, script, str(i), str(n), peers,
                 str(ports[i]), str(n_batches), str(batch)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                env=env) for i in range(n)]
            outs = [p.communicate(timeout=900)[0].decode() for p in ps]
            for i, p in enumerate(ps):
                if p.returncode:
                    raise RuntimeError(
                        f"q5-scale worker {i}/{n} rc={p.returncode}:\n"
                        + outs[i][-2000:])
            walls = [_json.loads(o.strip().splitlines()[-1])["wall_s"]
                     for o in outs]
            # the fleet DIVIDES the 2-split stream (local enumeration:
            # process p reads splits p, p+n, ...), so total events are
            # identical across fleet widths; throughput = total events
            # over the slowest member (the rendezvous barrier means
            # members finish together anyway)
            return 2 * n_batches * batch / max(walls)

        eps1 = fleet(1)
        epsn = fleet(procs)
        ratio = epsn / eps1
        emit("dcn_q5_events_per_sec", eps1, "events/sec", n_processes=1)
        emit("dcn_q5_events_per_sec", epsn, "events/sec",
             n_processes=procs)
        emit("dcn_q5_scaling", ratio, "ratio", n_processes=procs,
             target_met=ratio > 1.0,
             note="throughput must scale with process count "
                  "(ROADMAP item 2); parity is tier-1's job")
    if artifact:
        _write_artifact(artifact, "dcn_q5_scaling", rows)
    return rows


def main() -> None:
    import os

    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the axon site hook re-selects the TPU regardless of the env
        # var; pin at the config level before the backend initializes
        # (same trick as tests/conftest.py) so the virtual-mesh run of
        # the exchange benchmark actually sees its devices
        import jax

        jax.config.update("jax_platforms", "cpu")
    bench_state_update()
    bench_all_to_all()
    bench_codec()
    bench_columnar(artifact="BENCH_COLUMNAR.json")
    bench_fire_flush()
    bench_control(artifact="BENCH_CONTROL.json")
    bench_checkpoint()
    bench_dcn(artifact="BENCH_DCN.json")
    bench_dcn_q5(artifact="BENCH_DCN_Q5.json")


if __name__ == "__main__":
    main()
